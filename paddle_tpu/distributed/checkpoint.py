"""Fault-tolerant distributed (sharded) checkpointing + auto-resume.

Reference analogs: GroupSharded save paths (each rank persists its shard),
python/paddle/framework/io.py:646 (>4GB chunked pickle), and
fluid/incubate/checkpoint/auto_checkpoint.py:72 (periodic job snapshots with
automatic resume by job id). The reference's elastic manager restarts jobs by
"checkpoint + relaunch" — which only works if a snapshot interrupted by the
crash can never be mistaken for a resume target. This module provides that
guarantee:

* **Atomic commits** — a snapshot is written into ``step_<N>.tmp``, fsynced,
  renamed to ``step_<N>``, and only then stamped with a ``COMMIT`` manifest
  (schema version, step, world size, per-file SHA-256 + sizes). A snapshot
  without a valid manifest does not exist as far as
  :func:`latest_checkpoint`/:func:`load_checkpoint` are concerned; a crash at
  ANY point leaves either a ``.tmp`` dir or a manifest-less dir — never a
  resume candidate (the resume scan quarantines the latter as evidence).
* **Verification + quarantine** — auto-resume re-hashes the manifest's files
  before restoring; a torn or bit-rotted snapshot is renamed to
  ``step_<N>.corrupt`` (evidence, not a resume candidate) and resume falls
  back to the previous committed snapshot.
* **Async saves** — :class:`AsyncCheckpointer` snapshots device arrays to
  host synchronously (cheap), then runs the TensorStore/pickle writes on a
  background thread with at most one save in flight; ``wait()`` is the
  barrier and write errors surface on the next ``save()`` or at ``close()``.
* **Retry** — transient filesystem errors retry with exponential backoff +
  jitter (:class:`paddle_tpu.utils.retry.RetryPolicy`).

TPU-native: payloads containing SHARDED state (ZeRO moments/masters,
multi-host arrays) are persisted per shard through
:mod:`paddle_tpu.distributed.reshard` — every rank writes only its
host-addressable blocks under a rank-indexed block map, restore reshards
them directly onto the CURRENT mesh (so a snapshot taken at world size N
resumes at M), and multi-rank jobs commit POD-wide: rank 0 stamps the
COMMIT manifest only after every rank acked a durable payload through the
launcher's KV master. Replicated/unsharded payloads keep the legacy layout
(Orbax ``model/``, pickle ``optimizer.pdopt``), so a 1.3B+ ZeRO run still
checkpoints without materializing full-size state anywhere.

Fault injection (tests only): the module routes its state-changing filesystem
calls through the ``_fs`` seam (monkeypatch to inject transient errors), and
honors ``PADDLE_CKPT_FAULT=<stage>:<step>`` (stage ``die_before_rename`` or
``die_before_commit``) by SIGKILLing itself mid-save — the torn-write drill
behind the kill-and-resume e2e test.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import monitor as _monitor
from ..core.tensor import Tensor
from ..utils.retry import RetryPolicy
from . import reshard as _reshard

__all__ = ["save_state_dict", "load_state_dict", "save_checkpoint",
           "load_checkpoint", "latest_checkpoint", "committed_steps",
           "read_manifest", "verify_snapshot", "AsyncCheckpointer",
           "CheckpointError", "MANIFEST_NAME", "SCHEMA_VERSION"]

SCHEMA_VERSION = 1
MANIFEST_NAME = "COMMIT"
_HASH_CHUNK = 1 << 20


class CheckpointError(RuntimeError):
    """A snapshot could not be loaded/validated; the message names the
    snapshot and what exactly is wrong with it."""


class _Filesystem:
    """Fault-injection seam: every state-changing filesystem call of the
    commit protocol goes through here so tests can inject transient errors
    (fail N times), truncation, or death without touching the real fs API."""

    open = staticmethod(open)
    replace = staticmethod(os.replace)
    fsync = staticmethod(os.fsync)
    rename = staticmethod(os.rename)


_fs = _Filesystem()


def _maybe_die(stage: str, step: int):
    """PADDLE_CKPT_FAULT=<stage>:<step> → SIGKILL ourselves right here.
    Emulates preemption/power loss at the two interesting commit-protocol
    windows; only tests set the env var."""
    if os.environ.get("PADDLE_CKPT_FAULT") == f"{stage}:{step}":
        os.kill(os.getpid(), signal.SIGKILL)


def _default_retry() -> RetryPolicy:
    return RetryPolicy(max_attempts=3, base_delay=0.1, max_delay=2.0,
                       retry_on=(OSError,))


def _to_arrays(state: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (v.value() if isinstance(v, Tensor) else v)
            for k, v in state.items()}


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_state_dict(state_dict: Dict[str, Any], path: str):
    """Sharded save: each process writes its own shards (Orbax/TensorStore)."""
    ckptr = _ckptr()
    ckptr.save(os.path.abspath(path), _to_arrays(state_dict), force=True)


def load_state_dict(path: str, state_dict: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Restore; when `state_dict` (a template with live placements) is given,
    arrays restore DIRECTLY onto those shardings (resharding on load).

    Shapes are validated against the checkpoint's metadata first: restoring
    through a mismatched template would otherwise silently truncate/pad the
    saved arrays to the template shape — corruption, not an error."""
    import orbax.checkpoint as ocp
    ckptr = _ckptr()
    path = os.path.abspath(path)
    if state_dict is None:
        return ckptr.restore(path)
    try:
        saved_meta = ckptr.metadata(path)
    except Exception:
        saved_meta = None  # older orbax: restore still works, unvalidated
    template = {}
    for k, v in state_dict.items():
        arr = v.value() if isinstance(v, Tensor) else v
        if isinstance(saved_meta, dict):
            saved_shape = getattr(saved_meta.get(k), "shape", None)
            if saved_shape is not None \
                    and tuple(saved_shape) != tuple(arr.shape):
                raise ValueError(
                    f"load_state_dict: {k!r} is {tuple(arr.shape)} in this "
                    f"model but {tuple(saved_shape)} in the checkpoint "
                    f"({path}) — the snapshot does not fit this network")
        template[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                           sharding=arr.sharding)
    restored = ckptr.restore(path, restore_args=ocp.checkpoint_utils
                             .construct_restore_args(template))
    for k, v in state_dict.items():
        if isinstance(v, Tensor) and k in restored:
            v._data = restored[k]
    return restored


# --------------------------------------------------------------- commit proto

_STEP_RE = re.compile(r"^step_(\d+)$")


def _snapshot_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def _world_size() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    except ValueError:
        return 1


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_HASH_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(base: str) -> List[str]:
    """Relative (posix-separated) paths of every regular file under base,
    excluding the manifest itself and its tmp."""
    out = []
    for root, _dirs, files in os.walk(base):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), base)
            rel = rel.replace(os.sep, "/")
            if rel in (MANIFEST_NAME, MANIFEST_NAME + ".tmp"):
                continue
            out.append(rel)
    return sorted(out)


def _fsync_tree(base: str):
    """fsync every file, then every directory, bottom-up — the payload must
    be durable BEFORE the rename publishes it."""
    for root, dirs, files in os.walk(base, topdown=False):
        for name in files:
            fd = os.open(os.path.join(root, name), os.O_RDONLY)
            try:
                _fs.fsync(fd)
            finally:
                os.close(fd)
        fd = os.open(root, os.O_RDONLY)
        try:
            _fs.fsync(fd)
        finally:
            os.close(fd)


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        _fs.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _build_manifest(base: str, step: int, hash_files: bool = True) -> dict:
    files = {}
    for rel in _walk_files(base):
        p = os.path.join(base, rel.replace("/", os.sep))
        files[rel] = {"sha256": _file_sha256(p) if hash_files else None,
                      "bytes": os.path.getsize(p)}
    return {"schema": SCHEMA_VERSION, "step": int(step),
            "world_size": _world_size(), "wall": time.time(), "files": files}


def _write_manifest(base: str, manifest: dict):
    tmp = os.path.join(base, MANIFEST_NAME + ".tmp")
    with _fs.open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        _fs.fsync(f.fileno())
    _fs.replace(tmp, os.path.join(base, MANIFEST_NAME))
    _fsync_dir(base)


def read_manifest(base: str) -> Optional[dict]:
    """The snapshot's COMMIT manifest, or None when the snapshot is
    uncommitted (torn, in-progress, or pre-manifest legacy)."""
    path = os.path.join(base, MANIFEST_NAME)
    try:
        with open(path) as f:
            m = json.load(f)
        if not isinstance(m, dict) or not isinstance(m.get("files"), dict):
            return None
        if int(m.get("schema", -1)) > SCHEMA_VERSION:
            return None  # written by a future version we cannot validate
        name = os.path.basename(os.path.normpath(base))
        mm = _STEP_RE.match(name)
        if mm and m.get("step") is not None \
                and int(m["step"]) != int(mm.group(1)):
            return None  # manifest does not belong here (copied/renamed)
    except (OSError, ValueError, TypeError):
        # unreadable, or rotted fields that still parse as JSON (a string
        # schema/step): uncommitted either way — resume must not crash on it
        return None
    return m


def verify_snapshot(base: str, manifest: Optional[dict] = None) -> List[str]:
    """Re-hash a snapshot against its manifest. Returns problem strings
    (empty == verified committed snapshot)."""
    if manifest is None:
        manifest = read_manifest(base)
    if manifest is None:
        if not os.path.isdir(base):
            return [f"{base}: snapshot directory does not exist"]
        return [f"{base}: no {MANIFEST_NAME} manifest "
                f"(torn or in-progress save)"]
    problems = []
    for rel, meta in sorted(manifest["files"].items()):
        p = os.path.join(base, rel.replace("/", os.sep))
        if not os.path.isfile(p):
            problems.append(f"{base}: missing file {rel}")
            continue
        size = os.path.getsize(p)
        if size != meta.get("bytes"):
            problems.append(f"{base}: {rel} is {size} bytes, manifest says "
                            f"{meta.get('bytes')} (truncated?)")
            continue
        # emergency manifests record sizes only (sha256 null)
        if meta.get("sha256") and _file_sha256(p) != meta["sha256"]:
            problems.append(f"{base}: {rel} checksum mismatch")
    return problems


# --------------------------------------------------------------- state capture

def _fully_addressable(a) -> bool:
    """Seam for the shard-staging decision (tests monkeypatch this to
    exercise the multi-host staging path on a single-host mesh)."""
    return getattr(a, "is_fully_addressable", True)


def _needs_shard_stage(a) -> bool:
    """True when this array must be persisted per shard: it spans devices
    this process cannot address, or its NamedSharding actually splits a
    dimension (ZeRO moments/masters). Mesh-replicated and single-device
    arrays stay on the legacy whole-array path."""
    if not isinstance(a, jax.Array):
        return False
    if not _fully_addressable(a):
        return True
    return _reshard.is_sharded_array(a)


def _host_copy(obj):
    """Deep-copy a state structure to host — the async writer's snapshot,
    immune to subsequent training steps and device donation.

    Sharded arrays (and arrays spanning NON-addressable devices) are staged
    PER SHARD: only the blocks this host can address are copied to numpy
    (:class:`reshard.StagedArray`), never a live jax reference and never an
    assembled full-size buffer — closing the PR 4 carve-out where multi-host
    arrays kept device buffers pinned until the background write finished."""
    if isinstance(obj, Tensor):
        obj = obj.value()
    if isinstance(obj, jax.Array):
        if _needs_shard_stage(obj):
            return _reshard.stage(obj)
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _host_copy(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        c = [_host_copy(v) for v in obj]
        return c if isinstance(obj, list) else tuple(c)
    return obj


def _payload_is_sharded(state) -> bool:
    """Route a payload to the per-shard format when ANY leaf needs it (a
    staged shard copy, or a live sharded array on the sync path)."""
    if isinstance(state, _reshard.StagedArray):
        return True
    if isinstance(state, Tensor):
        return _needs_shard_stage(state.value())
    if isinstance(state, jax.Array):
        return _needs_shard_stage(state)
    if isinstance(state, dict):
        return any(_payload_is_sharded(v) for v in state.values())
    if isinstance(state, (list, tuple)):
        return any(_payload_is_sharded(v) for v in state)
    return False


def _capture(model, optimizer, grad_scaler, extra
             ) -> Tuple[Optional[dict], Optional[dict], dict]:
    model_state = dict(model.state_dict()) if model is not None else None
    opt_state = (optimizer.state_dict()
                 if optimizer is not None and hasattr(optimizer, "state_dict")
                 else None)
    ex = dict(extra or {})
    if grad_scaler is not None and hasattr(grad_scaler, "state_dict"):
        ex["grad_scaler"] = grad_scaler.state_dict()
    return model_state, opt_state, ex


# ------------------------------------------------------------------ write path

def _process_index() -> int:
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def _write_payloads(tmp: str, rank: int, model_state, opt_state, extra,
                    lead: Optional[bool] = None):
    """Write one rank's payload files under the snapshot tmp dir.

    Sharded payloads (any leaf is shard-staged or a live sharded array) go
    through the per-shard format — every rank persists its own blocks under
    ``<payload>.shards/rank_<r>/`` with a rank-indexed block map. Unsharded
    payloads keep the legacy single-writer layout (``model/`` via Orbax,
    ``optimizer.pdopt``/``extra.pkl`` pickles), written only by the
    ``lead`` writer (pod mode: rank 0 of the shared directory; single
    process / per-rank-private directories: this process, whatever its
    global rank — its directory must be self-contained)."""
    from .. import framework
    if lead is None:
        lead = rank == 0
    if model_state is not None:
        if _payload_is_sharded(model_state):
            _reshard.save_sharded(os.path.join(tmp, "model.shards"),
                                  model_state, rank=rank,
                                  write_skeleton=lead)
        elif lead:
            save_state_dict(model_state, os.path.join(tmp, "model"))
    if opt_state is not None:
        if _payload_is_sharded(opt_state):
            _reshard.save_sharded(os.path.join(tmp, "optimizer.shards"),
                                  opt_state, rank=rank,
                                  write_skeleton=lead)
        elif lead:
            framework.io.save(opt_state, os.path.join(tmp, "optimizer.pdopt"))
    if extra and lead:
        framework.io.save(extra, os.path.join(tmp, "extra.pkl"))


def _resolve_coordinator(coordinator):
    """An explicit coordinator wins; ``False`` forces the single-process
    commit even under the launcher env (the per-rank-private-directory
    layout); otherwise the launcher env contract (PADDLE_CKPT_MASTER +
    PADDLE_TRAINERS_NUM>1) builds one, else None."""
    if coordinator is False:
        return None
    if coordinator is not None:
        return coordinator
    return _reshard.pod_commit_from_env()


def _write_snapshot(directory: str, step: int, model_state, opt_state, extra,
                    retry: Optional[RetryPolicy], mode: str,
                    coordinator=None) -> str:
    """The commit protocol. Returns the committed snapshot path.

    Emergency saves (mode="emergency") skip per-file hashing: re-reading a
    multi-GB payload to checksum it would spend the preemption grace window
    on I/O that only guards against later bit-rot — their manifests record
    sizes only, which still catches truncation.

    With a pod coordinator (multi-rank jobs over the launcher's KV master),
    the COMMIT manifest is pod-wide: rank 0 stamps it only after every rank
    acked a durable payload — see :func:`_write_snapshot_pod`.

    ``coordinator`` here is ALREADY RESOLVED by the public entry points
    (``save_checkpoint``/``AsyncCheckpointer``): None means single-process
    commit — re-resolving from env here would silently re-enable the pod
    barrier after a caller opted out with ``coordinator=False``."""
    from .. import framework
    coord = coordinator
    if coord is not None and coord.world > 1:
        return _write_snapshot_pod(directory, step, model_state, opt_state,
                                   extra, retry, mode, coord)
    t0 = time.perf_counter()
    final = _snapshot_dir(directory, step)
    tmp = final + ".tmp"
    old = final + ".old"
    hash_files = mode != "emergency"

    attempts = {"n": 0}

    def body():
        attempts["n"] += 1
        if attempts["n"] > 1:
            mon = _monitor._active
            if mon is not None:
                mon.ckpt_retry(step, attempts["n"] - 1)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        # single-process commit: this directory is self-contained — stage
        # blocks under the REAL process rank (a multi-host job using
        # per-rank-private dirs must not filter its own blocks out against
        # a hardcoded rank 0) and write the skeleton/legacy payloads here
        _write_payloads(tmp, _process_index(), model_state, opt_state,
                        extra, lead=True)
        _fsync_tree(tmp)
        _maybe_die("die_before_rename", step)
        if os.path.isdir(final):
            # only ever a torn payload from a previous ATTEMPT of this call
            # (the pre-existing committed snapshot is parked at .old)
            shutil.rmtree(final, ignore_errors=True)
        _fs.replace(tmp, final)          # atomic publish of the payload
        _fsync_dir(directory)
        _maybe_die("die_before_commit", step)
        manifest = _build_manifest(final, step, hash_files)
        _write_manifest(final, manifest)  # the snapshot now EXISTS
        return manifest

    policy = retry or _default_retry()
    with _aside_lock:  # _recover_aside must not "heal" this live window
        # Re-saving an existing step (post-rollback): park the current
        # snapshot at .old ONCE, before any attempt — inside the retry body
        # it would see its own torn earlier attempt at `final` and destroy
        # the parked copy. It is dropped only after the new COMMIT lands;
        # _recover_aside puts it back if we die in between.
        if os.path.isdir(final):
            if os.path.isdir(old):
                shutil.rmtree(old, ignore_errors=True)
            _fs.rename(final, old)
        try:
            manifest = policy(body)
        except BaseException:
            # a persistently-failing RE-save must not strand the previously
            # committed snapshot at .old (invisible to resume): put it back
            # — including over a published-but-never-committed (torn) new
            # payload, which the committed old strictly beats
            if os.path.isdir(old):
                try:
                    if os.path.isdir(final):
                        shutil.rmtree(final, ignore_errors=True)
                    _fs.rename(old, final)
                except OSError:
                    pass
            raise
        if os.path.isdir(old):  # replaced snapshot, kept until the commit
            shutil.rmtree(old, ignore_errors=True)
    mon = _monitor._active
    if mon is not None:
        mon.ckpt_saved(step=step,
                       nbytes=sum(f["bytes"]
                                  for f in manifest["files"].values()),
                       dur_s=time.perf_counter() - t0, mode=mode,
                       attempts=attempts["n"])
    return final


def _write_snapshot_pod(directory: str, step: int, model_state, opt_state,
                        extra, retry: Optional[RetryPolicy], mode: str,
                        coord) -> str:
    """Pod-wide commit (multi-rank, shared filesystem, KV master barrier).

    Rank 0 owns the directory protocol — tmp dir, rename, manifest, COMMIT
    — exactly as in the single-process path; every other rank only writes
    its own per-shard payload into the tmp dir and acks through the KV
    master. The COMMIT manifest lands strictly after the last ack, so a
    crash of ANY rank before that point leaves a manifest-less (invisible)
    directory on every rank; the ack key itself is only PUT after the
    rank's payload is written and fsynced (the "durable" half of the
    barrier). Retry covers each rank's local payload writes; barrier
    timeouts raise :class:`CheckpointError` with the missing ranks named.

    Known limit: the re-save set-aside window (``step_N.old``) is guarded
    by an in-process lock on rank 0 only — a sibling rank running the
    resume scan DURING rank 0's re-save of an already-committed step could
    heal the window early. Re-saves only happen post-rollback and resume
    scans only at startup, so the orderings don't overlap in the launcher
    lifecycle; a cross-process lease through the KV master is the upgrade
    path if that ever changes."""
    t0 = time.perf_counter()
    coord = coord.for_dir(directory)  # keys scoped to THIS snapshot dir
    final = _snapshot_dir(directory, step)
    tmp = final + ".tmp"
    old = final + ".old"
    hash_files = mode != "emergency"
    policy = retry or _default_retry()
    mon = _monitor._active

    if coord.rank != 0:
        try:
            token = coord.wait_ready(step)

            def body():
                _write_payloads(tmp, coord.rank, model_state, opt_state,
                                extra)
                _fsync_tree(tmp)

            policy(body)
            _maybe_die("die_before_ack", step)
            coord.ack(step, token, {"mode": mode})
            res = coord.wait_commit(step, token)
        except _reshard.PodCommitError as e:
            raise CheckpointError(str(e)) from e
        if mon is not None:
            mon.ckpt_saved(step=step, nbytes=0,
                           dur_s=time.perf_counter() - t0, mode=mode)
        return res.get("path", final)

    with _aside_lock:  # same re-save set-aside protocol as single-process
        if os.path.isdir(final):
            if os.path.isdir(old):
                shutil.rmtree(old, ignore_errors=True)
            _fs.rename(final, old)
        try:
            def body():
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp)
                _write_payloads(tmp, 0, model_state, opt_state, extra)
                _fsync_tree(tmp)

            policy(body)
            # the barrier opens only after rank 0's payload is durable AND
            # the retry loop is done (a retry would rmtree the tmp dir out
            # from under the other ranks' writes)
            token = coord.publish_ready(step)
            acks = coord.wait_acks(step, token)
            _maybe_die("die_before_rename", step)
            if os.path.isdir(final):
                shutil.rmtree(final, ignore_errors=True)
            _fs.replace(tmp, final)      # atomic publish of the pod payload
            _fsync_dir(directory)
            _maybe_die("die_before_commit", step)
            manifest = _build_manifest(final, step, hash_files)
            manifest["ranks"] = sorted([0] + list(acks))
            _write_manifest(final, manifest)  # the snapshot now EXISTS
        except BaseException as e:
            if os.path.isdir(old):
                try:
                    if os.path.isdir(final):
                        shutil.rmtree(final, ignore_errors=True)
                    _fs.rename(old, final)
                except OSError:
                    pass
            if isinstance(e, _reshard.PodCommitError):
                raise CheckpointError(str(e)) from e
            raise
        if os.path.isdir(old):
            shutil.rmtree(old, ignore_errors=True)
    # past this point the snapshot is COMMITTED on disk: announcing it to
    # the waiting ranks happens outside the rollback try above, so no
    # announcement failure can ever restore .old over a committed snapshot
    coord.publish_commit(step, token, final)
    if mon is not None:
        mon.ckpt_saved(step=step,
                       nbytes=sum(f["bytes"]
                                  for f in manifest["files"].values()),
                       dur_s=time.perf_counter() - t0, mode=mode)
    return final


def _prune_committed(directory: str, keep: int, protect: str):
    """Prune to the newest `keep` snapshots by mtime (NOT step number — a
    post-rollback save with a lower step must survive). Only COMMITTED
    snapshots are prunable: an in-flight ``.tmp``, a torn manifest-less dir
    (evidence for the operator) and quarantined ``.corrupt`` dirs are never
    touched, and the snapshot just written never prunes itself."""
    if not keep or not os.path.isdir(directory):
        return
    protect = os.path.abspath(protect)
    entries = []
    for d in os.listdir(directory):
        if not _STEP_RE.match(d):
            continue
        p = os.path.join(directory, d)
        if os.path.abspath(p) == protect:
            continue
        if read_manifest(p) is None:
            continue
        entries.append((os.path.getmtime(p), p))
    for _, p in sorted(entries, reverse=True)[max(keep - 1, 0):]:
        shutil.rmtree(p, ignore_errors=True)


def save_checkpoint(directory: str, step: int, model=None, optimizer=None,
                    extra: Optional[Dict[str, Any]] = None, keep: int = 3,
                    grad_scaler=None, retry: Optional[RetryPolicy] = None,
                    coordinator=None, _mode: str = "sync") -> str:
    """Periodic job snapshot: <dir>/step_<N>/{model,optimizer.pdopt,extra.pkl}
    committed atomically under a COMMIT manifest (reference auto_checkpoint).
    Sharded state (ZeRO moments/masters, multi-host arrays) is persisted
    per shard under ``<payload>.shards/`` instead — see
    :mod:`paddle_tpu.distributed.reshard`. Prunes committed snapshots beyond
    the newest `keep`. A ``grad_scaler``'s state rides in
    ``extra["grad_scaler"]`` and is restored by :func:`load_checkpoint`.

    ``coordinator``: a :class:`reshard.PodCommit` for multi-rank jobs
    sharing one snapshot directory (defaults from the launcher env — the
    COMMIT manifest then lands only after every rank's payload is durable).
    Returns the committed snapshot path."""
    model_state, opt_state, ex = _capture(model, optimizer, grad_scaler, extra)
    coord = _resolve_coordinator(coordinator)
    final = _write_snapshot(directory, step, model_state, opt_state, ex,
                            retry, _mode, coordinator=coord)
    if coord is None or coord.rank == 0:
        _prune_committed(directory, keep, final)
    return final


# -------------------------------------------------------------------- resume

_OLD_RE = re.compile(r"^step_(\d+)\.old$")

# Serializes the re-save set-aside window against the recovery scan: while a
# writer in THIS process is mid-protocol (parked .old, payload in flight), a
# concurrent latest_checkpoint() must not "heal" the live window — it would
# rename the .old back and the writer's retry would then destroy it. Cross-
# process writers are out of scope (one writer per checkpoint dir is the
# contract: each rank owns its own directory).
_aside_lock = threading.Lock()


def _recover_aside(directory: str):
    """Heal crashes inside a re-save's set-aside window: a COMMITTED
    ``step_<N>.old`` whose replacement never committed is the real snapshot
    — quarantine the torn replacement and rename the parked copy back. A
    leftover ``.old`` beside a committed replacement is just cleanup."""
    if not os.path.isdir(directory):
        return
    if not _aside_lock.acquire(blocking=False):
        return  # a live writer owns the window; there is no crash to heal
    try:
        _recover_aside_locked(directory)
    finally:
        _aside_lock.release()


def _recover_aside_locked(directory: str):
    for d in os.listdir(directory):
        m = _OLD_RE.match(d)
        if not m:
            continue
        oldp = os.path.join(directory, d)
        finalp = _snapshot_dir(directory, int(m.group(1)))
        if read_manifest(finalp) is not None:
            shutil.rmtree(oldp, ignore_errors=True)
        elif read_manifest(oldp) is not None:
            if os.path.isdir(finalp):
                _quarantine(finalp, [f"{finalp}: torn re-save superseded by "
                                     f"the parked committed copy"])
            try:
                _fs.rename(oldp, finalp)
            except OSError:
                pass
        # both uncommitted: leave the evidence alone


def committed_steps(directory: str) -> List[int]:
    """Steps with a valid COMMIT manifest, ascending. Torn/partial dirs and
    ``.tmp``/``.corrupt`` entries are invisible here by construction."""
    if not os.path.isdir(directory):
        return []
    _recover_aside(directory)
    steps = []
    for d in os.listdir(directory):
        m = _STEP_RE.match(d)
        if m and read_manifest(os.path.join(directory, d)) is not None:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_checkpoint(directory: str) -> Optional[int]:
    """Newest COMMITTED step — a crash mid-save can never surface here."""
    steps = committed_steps(directory)
    return steps[-1] if steps else None


def _quarantine(base: str, problems: List[str]):
    dst = base + ".corrupt"
    i = 0
    while os.path.exists(dst):
        i += 1
        dst = base + f".corrupt.{i}"
    try:
        _fs.rename(base, dst)
    except OSError:
        dst = None  # cannot move it; resume still skips it this run
    mon = _monitor._active
    if mon is not None:
        mon.ckpt_corrupt(base, "; ".join(problems), quarantined=dst)
    return dst


def _load_sharded_model(path: str, model, force_gather: bool):
    """Reshard a per-shard model payload onto the live params' placements.

    Every live state entry MUST have a snapshot entry: silently leaving a
    param at its init value (a model grew a weight since the snapshot)
    would resume training with one random tensor at full confidence — the
    legacy Orbax path errors on that, and so does this one."""
    sd = dict(model.state_dict())
    template = {}
    for k, v in sd.items():
        template[json.dumps([k])] = v.value() if isinstance(v, Tensor) else v
    flat, _skel, stats = _reshard.load_sharded(path, template,
                                               force_gather=force_gather)
    missing = [k for k in sd if json.dumps([k]) not in flat]
    if missing:
        raise ValueError(
            f"{path}: snapshot has no entry for model state "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''} — the "
            f"snapshot does not fit this network (did the model grow?)")
    for k, v in sd.items():
        key = json.dumps([k])
        if isinstance(v, Tensor):
            v._data = flat[key]
    return stats


def _load_sharded_opt(path: str, optimizer, force_gather: bool):
    """Reshard per-shard optimizer state onto the CURRENT mesh: states are
    first materialized at their shard-sized placements (the ZeRO
    ``_state_placement_fn`` hook, PR 5), which become the reshard targets —
    an N-way snapshot's moments/masters land directly at the M-way layout,
    no transient full-size buffer on the nestable paths."""
    ensure = getattr(optimizer, "_ensure_all_states", None)
    if ensure is not None:
        ensure()
    placer = getattr(optimizer, "_place_states", None)
    if placer is not None:
        placer()
    template, _ = _reshard.flatten_state(optimizer.state_dict()) \
        if hasattr(optimizer, "state_dict") else ({}, None)
    flat, skel, stats = _reshard.load_sharded(path, template,
                                              force_gather=force_gather)
    if skel is None:
        raise CheckpointError(
            f"{path}: sharded optimizer payload has no skeleton.pkl "
            f"(rank 0's payload missing) — cannot rebuild the state dict")
    optimizer.set_state_dict(_reshard.unflatten_state(skel, flat))
    return stats


def _merge_reshard_stats(stats_list) -> Dict[str, Any]:
    agg = _reshard.ReshardStats()
    for s in stats_list:
        agg.arrays += s.arrays
        agg.identity += s.identity
        agg.mapped += s.mapped
        agg.gathered += s.gathered
        agg.nestable_gather += s.nestable_gather
        agg.bytes_read += s.bytes_read
        agg.src_world = max(agg.src_world, s.src_world)
        agg.dst_world = max(agg.dst_world, s.dst_world)
        agg.wall_s += s.wall_s
    return agg.as_dict()


def _restore(base: str, step: int, model, optimizer, grad_scaler,
             force_gather: bool = False) -> Dict[str, Any]:
    from .. import framework
    reshard_stats = []
    try:
        if model is not None:
            mshards = os.path.join(base, "model.shards")
            mpath = os.path.join(base, "model")
            if os.path.isdir(mshards):
                reshard_stats.append(
                    _load_sharded_model(mshards, model, force_gather))
            elif os.path.isdir(mpath):
                load_state_dict(mpath, dict(model.state_dict()))
            else:
                raise CheckpointError(
                    f"snapshot {base} has no 'model/' payload (partial save "
                    f"or a model-less snapshot) — cannot restore model "
                    f"weights from it")
        info: Dict[str, Any] = {"step": step}
        oshards = os.path.join(base, "optimizer.shards")
        opt_path = os.path.join(base, "optimizer.pdopt")
        if optimizer is not None and os.path.isdir(oshards):
            reshard_stats.append(
                _load_sharded_opt(oshards, optimizer, force_gather))
        elif optimizer is not None and os.path.exists(opt_path):
            optimizer.set_state_dict(framework.io.load(opt_path))
        if model is not None and optimizer is not None:
            # ZeRO eager path: the model restore COMMITS params to the
            # placement they were saved at (possibly pre-mesh single-device
            # from a different world size), while the optimizer states live
            # at this mesh's shard placement — a mixed-device fused update
            # would be rejected. The sharding wrapper's own all-gather-
            # after-step placement rule re-places params onto this mesh
            # (mesh placements kept, pre-mesh params -> mesh-replicated);
            # compiled TrainStep re-commits in __init__ and is unaffected.
            replace = getattr(optimizer, "_restore_param_placements", None)
            if replace is not None:
                replace()
    except (_reshard.PartialSnapshotError, FileNotFoundError) as e:
        # PARTIAL coverage / missing index from the sharded reader behaves
        # like a torn save: a diagnostic CheckpointError, so auto-resume
        # falls back past it. A template SHAPE mismatch (plain ValueError —
        # the snapshot does not fit this network) stays a loud error: a
        # wrong-architecture resume must never silently start fresh.
        raise CheckpointError(f"snapshot {base}: {e}") from e
    extra_path = os.path.join(base, "extra.pkl")
    if os.path.exists(extra_path):
        info.update(framework.io.load(extra_path, return_numpy=True))
    if grad_scaler is not None and isinstance(info.get("grad_scaler"), dict):
        grad_scaler.load_state_dict(info["grad_scaler"])
    mon = _monitor._active
    if reshard_stats:
        info["reshard"] = _merge_reshard_stats(reshard_stats)
        if mon is not None:
            mon.reshard_loaded(**info["reshard"])
    if mon is not None:
        mon.ckpt_resumed(step, base)
    return info


def load_checkpoint(directory: str, model=None, optimizer=None,
                    step: Optional[int] = None, grad_scaler=None,
                    verify: bool = True, quarantine: bool = True,
                    force_gather: bool = False,
                    max_step: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
    """Resume from the newest committed snapshot (or the given ``step``).

    Auto-resume (``step=None``) verifies checksums, quarantines anything
    torn or corrupt (renamed ``step_<N>.corrupt``) and falls back to the
    previous committed snapshot; returns ``{'step': N, **extra}`` or None
    when nothing committed is loadable. An EXPLICIT ``step`` that is
    missing, uncommitted or fails verification raises :class:`CheckpointError`
    with a diagnostic naming the snapshot — never an opaque backend error;
    ``step=N, verify=False`` is the operator override that restores a
    manifest-less snapshot anyway.

    ``max_step`` bounds auto-resume: snapshots with a LARGER step are
    skipped untouched — not verified, never quarantined — and the newest
    committed snapshot at or below the bound restores. This is the
    health plane's quarantine-the-spike-step rollback primitive
    (``monitor/health.py``): snapshots taken after a loss spike may hold
    poisoned weights, but they are suspect, not corrupt, so they stay on
    disk for the post-mortem. Ignored when ``step`` is explicit.

    Directories written BEFORE the commit protocol hold manifest-less
    snapshots, which auto-resume treats exactly like torn saves (skipped and
    quarantined — renamed, never deleted). Upgrade by loading the newest one
    explicitly with ``verify=False`` and re-saving it committed."""
    if step is not None:
        base = _snapshot_dir(directory, step)
        if not os.path.isdir(base):
            raise CheckpointError(
                f"snapshot {base} does not exist "
                f"(committed steps here: {committed_steps(directory)})")
        manifest = read_manifest(base)
        if manifest is None:
            if not verify:
                # operator escape hatch: an EXPLICIT step with verify=False
                # restores a manifest-less snapshot best-effort (pre-manifest
                # legacy dirs, or salvage from a quarantine copy)
                return _restore(base, step, model, optimizer, grad_scaler,
                                force_gather)
            missing = [] if os.path.isdir(os.path.join(base, "model")) \
                else ["model/"]
            raise CheckpointError(
                f"snapshot {base} is not committed: no {MANIFEST_NAME} "
                f"manifest" + (f" and {missing[0]} is missing" if missing
                               else "") +
                " — a save was interrupted here (or it predates the commit "
                "protocol); pick a committed step "
                f"({committed_steps(directory)}), let auto-resume "
                "(step=None) fall back past it, or force this one with "
                "verify=False if you trust it")
        if verify:
            problems = verify_snapshot(base, manifest)
            if problems:
                raise CheckpointError(
                    "snapshot failed verification: " + "; ".join(problems))
        return _restore(base, step, model, optimizer, grad_scaler,
                                force_gather)

    all_steps = []
    if os.path.isdir(directory):
        _recover_aside(directory)
        for d in os.listdir(directory):
            m = _STEP_RE.match(d)
            if m:
                all_steps.append(int(m.group(1)))
    for s in sorted(all_steps, reverse=True):
        if max_step is not None and s > max_step:
            continue                       # suspect, not corrupt: untouched
        base = _snapshot_dir(directory, s)
        manifest = read_manifest(base)
        if manifest is None:
            problems = [f"{base}: no {MANIFEST_NAME} manifest "
                        f"(torn or in-progress save)"]
        else:
            problems = verify_snapshot(base, manifest) if verify else []
            if not problems and model is not None and \
                    not any(f.startswith("model/")
                            for f in manifest["files"]):
                # a HEALTHY snapshot that simply has no model payload
                # (saved with model=None): incompatible with this restore,
                # not corrupt — skip it but leave it alone
                continue
        if not problems:
            try:
                return _restore(base, s, model, optimizer, grad_scaler,
                                force_gather)
            except CheckpointError:
                # verified clean but incompatible with what the caller asked
                # to restore — skip without destroying valid history
                continue
        if quarantine:
            _quarantine(base, problems)
        else:
            mon = _monitor._active
            if mon is not None:
                mon.ckpt_corrupt(base, "; ".join(problems), quarantined=None)
    return None


# ------------------------------------------------------------------ async save

class AsyncCheckpointer:
    """Background checkpoint writer with at most ONE save in flight.

    ``save()`` snapshots the model/optimizer/scaler state to host numpy
    synchronously (so the training loop may mutate or donate device arrays
    immediately) and hands the filesystem work — TensorStore writes, fsync,
    manifest, prune — to a writer thread. A second ``save()`` while one is in
    flight first waits for it (the "at most one" barrier). A write error is
    raised on the NEXT ``save()``/``wait()``/``close()`` call, on the caller's
    thread — training never dies inside the writer.

    Usable as a context manager; ``close()`` (or ``__exit__``) is the
    shutdown barrier that surfaces the last error.
    """

    def __init__(self, directory: str, keep: int = 3,
                 retry: Optional[RetryPolicy] = None, coordinator=None):
        self.directory = directory
        self.keep = keep
        self._retry = retry
        # pod-wide commit for multi-rank jobs sharing this directory
        # (explicit wins; else the launcher env contract; else None)
        self._coordinator = _resolve_coordinator(coordinator)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_path: Optional[str] = None

    # ------------------------------------------------------------------- api

    def save(self, step: int, model=None, optimizer=None, grad_scaler=None,
             extra: Optional[Dict[str, Any]] = None, block: bool = False,
             _mode: Optional[str] = None) -> None:
        """Queue one snapshot. ``block=True`` writes synchronously on this
        thread (emergency saves want the barrier semantics of sync)."""
        self.wait()  # barrier: one in flight; raises a previous write error
        model_state, opt_state, ex = _capture(model, optimizer, grad_scaler,
                                              extra)
        model_state = _host_copy(model_state)
        opt_state = _host_copy(opt_state)
        ex = _host_copy(ex)
        mode = _mode or ("sync" if block else "async")

        def work():
            try:
                self._last_path = _write_snapshot(
                    self.directory, step, model_state, opt_state, ex,
                    self._retry, mode, coordinator=self._coordinator)
                if self._coordinator is None or self._coordinator.rank == 0:
                    _prune_committed(self.directory, self.keep,
                                     self._last_path)
            except BaseException as e:  # surfaced on the next call-in
                self._error = e

        if block:
            work()
            self._raise_pending()
            return
        t = threading.Thread(target=work, daemon=True,
                             name=f"ckpt-writer-step{step}")
        self._thread = t
        t.start()

    def wait(self):
        """Block until no save is in flight; re-raise any write error."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        self._raise_pending()

    def in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def last_path(self) -> Optional[str]:
        return self._last_path

    def close(self):
        self.wait()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc):
        # an exception is already unwinding: don't mask it with a stale
        # writer error, but do drain the thread
        if exc and exc[0] is not None:
            t = self._thread
            if t is not None:
                t.join()
                self._thread = None
            return False
        self.close()
        return False

    def _raise_pending(self):
        if self._error is not None:
            e, self._error = self._error, None
            raise e
