"""CLI entry: `python -m paddle_tpu.distributed.launch [options] script.py args...`

Reference analog: launch/main.py (fleetrun). Argument surface mirrors the subset of
launch/context/args_envs.py:53-179 that is meaningful on TPU fleets; PS/IPU-specific
groups are intentionally absent (the TPU build has no parameter-server runtime here).
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .controller import LaunchContext, PodController


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job (fleetrun analog)")
    p.add_argument("--master", default=None,
                   help="host:port of the rendezvous master (node 0 serves it)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (TPU idiom: 1/host; >1 for CPU sim)")
    p.add_argument("--node_rank", type=int, default=None,
                   help="explicit node rank (else registration order)")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--devices", default=None,
                   help="visible device selector, exported as PADDLE_DEVICES")
    p.add_argument("--max_restart", type=int, default=0,
                   help="restart the pod up to N times on failure (elastic L1)")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps"],
                   help="job kind (reference launch run_mode): ps spawns "
                        "parameter servers + trainers")
    p.add_argument("--server_num", type=int, default=0,
                   help="ps mode: parameter-server process count")
    p.add_argument("--trainer_num", type=int, default=0,
                   help="ps mode: trainer process count")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="1: scale the world in/out on worker loss "
                        "(reference fleet elastic manager semantics; workers "
                        "resume from their checkpoints)")
    p.add_argument("--min_np", type=int, default=1,
                   help="elastic floor: never scale below this worker count")
    p.add_argument("--max_np", type=int, default=0,
                   help="elastic ceiling for scale-out (0: nproc_per_node)")
    p.add_argument("--stop_grace", type=float,
                   default=float(os.environ.get("PADDLE_STOP_GRACE", "15")),
                   help="seconds between forwarding SIGTERM/SIGINT to ranks "
                        "(emergency-checkpoint window) and the hard kill")
    p.add_argument("--restart_backoff", type=float,
                   default=float(os.environ.get("PADDLE_RESTART_BACKOFF",
                                                "1")),
                   help="base seconds of the exponential backoff between "
                        "pod restarts (0 disables)")
    p.add_argument("script", nargs=argparse.REMAINDER,
                   help="training script (or -m module) and its args")
    return p


def launch(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    script = list(args.script)
    if script and script[0] == "--":
        script = script[1:]
    if not script:
        print("error: no training script given", file=sys.stderr)
        return 2
    ctx = LaunchContext(script=script, nnodes=args.nnodes,
                        nproc_per_node=args.nproc_per_node, master=args.master,
                        node_rank=args.node_rank, job_id=args.job_id,
                        log_dir=args.log_dir, devices=args.devices,
                        max_restart=args.max_restart, run_mode=args.run_mode,
                        server_num=args.server_num,
                        trainer_num=args.trainer_num,
                        elastic_level=args.elastic_level, min_np=args.min_np,
                        max_np=args.max_np, stop_grace=args.stop_grace,
                        restart_backoff=args.restart_backoff)
    return PodController(ctx).run()


def main() -> int:
    return launch(sys.argv[1:])
