"""Distributed launcher — the `fleetrun` analog.

Reference: python/paddle/distributed/launch/main.py (CLI), controllers/collective.py
(pod build + env contract), controllers/master.py:27,65 (HTTP KV rendezvous),
controllers/watcher.py (process supervision), phi/core/distributed/store/tcp_store.cc
(bootstrap KV).

TPU-native shape: the unit of launch is one process per HOST (jax owns every local
chip), not one per device — `--nproc_per_node` exists for CPU-simulation tests and
multi-slice hosts. Rank bootstrap = HTTP KV barrier; collective bootstrap =
`jax.distributed.initialize` against the coordinator (the TCPStore analog lives
inside jax's coordination service; we only have to agree on the address).
"""
from .main import launch, main  # noqa: F401
