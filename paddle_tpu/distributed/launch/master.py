"""HTTP KV master: rendezvous + barrier for multi-node launch.

Reference analog: launch/controllers/master.py (HTTPMaster over a KVServer) and the
TCPStore wait/set semantics (phi/core/distributed/store/tcp_store.cc). One node runs
the server; every node PUTs its endpoint under a job-scoped prefix and polls GET
until all peers registered — the result is a deterministic, rank-ordered peer list.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple


class KVServer:
    """Tiny in-memory KV over HTTP: PUT /k, GET /k, GET /prefix/ lists."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        store: Dict[str, bytes] = {}
        lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                with lock:
                    store[self.path] = body
                self.send_response(200)
                self.end_headers()

            def do_DELETE(self):
                with lock:
                    store.pop(self.path, None)
                self.send_response(200)
                self.end_headers()

            def do_GET(self):
                if self.path.endswith("/"):
                    with lock:
                        items = {k: v.decode() for k, v in store.items()
                                 if k.startswith(self.path)}
                    body = json.dumps(items).encode()
                    self.send_response(200)
                else:
                    with lock:
                        body = store.get(self.path)
                    if body is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._srv.shutdown()


class KVClient:
    """HTTP client for KVServer. ``timeout`` is per-call: rendezvous can
    afford the lazy default, but the serving router polls this store on
    its health cadence and needs a short bound so one slow master never
    stalls placement (serving/endpoint.py passes ~1s)."""

    def __init__(self, endpoint: str, timeout: float = 5.0):
        self._base = f"http://{endpoint}"
        self._timeout = float(timeout)

    def put(self, key: str, value: str) -> bool:
        req = urllib.request.Request(f"{self._base}{key}", data=value.encode(),
                                     method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return r.status == 200
        except OSError:
            return False

    def get(self, key: str) -> Optional[str]:
        try:
            with urllib.request.urlopen(f"{self._base}{key}",
                                        timeout=self._timeout) as r:
                return r.read().decode()
        except OSError:
            return None

    def delete(self, key: str) -> bool:
        req = urllib.request.Request(f"{self._base}{key}", method="DELETE")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return r.status == 200
        except OSError:
            return False

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        try:
            with urllib.request.urlopen(f"{self._base}{prefix}",
                                        timeout=self._timeout) as r:
                return json.loads(r.read().decode())
        except OSError:
            return {}


class Master:
    """Rendezvous: every node registers, waits for nnodes peers, gets rank order.

    Node 0 (the one whose --master address is local and free) hosts the KVServer
    in-process — reference HTTPMaster.launch() does exactly this.
    """

    def __init__(self, endpoint: str, job_id: str, nnodes: int):
        self.endpoint = endpoint
        self.job_id = job_id
        self.nnodes = nnodes
        self._server: Optional[KVServer] = None
        self._client = KVClient(endpoint)

    def maybe_serve(self) -> bool:
        host, port = self.endpoint.rsplit(":", 1)
        try:
            srv = KVServer(int(port))
        except OSError:
            return False  # someone else (node 0) already bound it
        self._server = srv
        srv.start()
        return True

    def sync_peers(self, my_endpoint: str, node_rank: Optional[int],
                   timeout: float = 300.0) -> Tuple[int, List[str]]:
        """Register and barrier until nnodes endpoints present.

        Returns (node_rank, ordered endpoint list). Explicit ranks win; otherwise
        registration order (ties broken by endpoint sort) assigns ranks.
        """
        prefix = f"/{self.job_id}/nodes/"
        key = f"{prefix}{node_rank if node_rank is not None else my_endpoint}"
        deadline = time.time() + timeout
        existing = self._client.get(key)
        if existing is not None and existing != my_endpoint:
            raise RuntimeError(
                f"node_rank {node_rank} already registered by {existing}: "
                f"duplicate --node_rank in job '{self.job_id}'")
        while not self._client.put(key, my_endpoint):
            if time.time() > deadline:
                raise TimeoutError(f"master {self.endpoint} unreachable")
            time.sleep(0.5)
        while True:
            peers = self._client.get_prefix(prefix)
            if len(peers) >= self.nnodes:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"rendezvous timeout: {len(peers)}/{self.nnodes} nodes")
            time.sleep(0.5)

        explicit = {k for k in peers if k[len(prefix):].isdigit()}
        if explicit and len(explicit) < len(peers):
            # mixing explicit and auto ranks would let two nodes claim one rank
            raise RuntimeError(
                "either every node or no node may pass --node_rank "
                f"(job '{self.job_id}': {len(explicit)}/{len(peers)} explicit)")

        def order_key(k: str):
            tail = k[len(prefix):]
            return (0, int(tail), "") if tail.isdigit() else (1, 0, tail)

        ordered = [peers[k] for k in sorted(peers, key=order_key)]
        if node_rank is not None:
            return int(node_rank), ordered
        return ordered.index(my_endpoint), ordered

    def stop(self):
        if self._server is not None:
            self._server.stop()
