"""Pod controller: spawn rank processes with the PADDLE_* env contract + watch them.

Reference analog: launch/controllers/collective.py (CollectiveController.build_pod
sets PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS/... per process), launch/job/pod.py
(process container) and controllers/watcher.py (liveness). Restart policy mirrors
the reference's `--max_restart` elastic knob at level 0/1.
"""
from __future__ import annotations

import os
import secrets
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...utils.retry import backoff_delay
from .master import Master

ENV_PREFIX = "PADDLE_"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def this_host() -> str:
    return socket.gethostbyname(socket.gethostname())


@dataclass
class LaunchContext:
    script: List[str]                      # script + its args (or -m module ...)
    nnodes: int = 1
    nproc_per_node: int = 1
    master: Optional[str] = None           # host:port of the KV/rendezvous server
    node_rank: Optional[int] = None
    job_id: str = "default"
    log_dir: Optional[str] = None
    devices: Optional[str] = None
    max_restart: int = 0
    run_mode: str = "collective"           # "collective" | "ps"
    server_num: int = 0
    trainer_num: int = 0
    envs: Dict[str, str] = field(default_factory=dict)
    elastic_level: int = 0                 # 1: scale world on worker loss
    min_np: int = 1                        # elastic floor
    max_np: int = 0                        # elastic ceiling (0: nproc_per_node)
    # preemption: on SIGTERM/SIGINT the controller forwards the signal to
    # every rank (so they can emergency-checkpoint) and waits this many
    # seconds before the hard kill
    stop_grace: float = 15.0
    # base delay of the exponential backoff between restarts (0 disables);
    # a deterministically-failing pod must not hot-loop its restart budget
    restart_backoff: float = 1.0


class PodController:
    """Builds and supervises the local pod (the node's rank processes)."""

    def __init__(self, ctx: LaunchContext):
        self.ctx = ctx
        self.procs: List[subprocess.Popen] = []
        self.logs: List[Optional[object]] = []
        self._master: Optional[Master] = None
        self._token: str = ""
        self._stop_signum: Optional[int] = None
        self._telemetry_srv = None          # controller-hosted KVServer
        self._telemetry_ep: Optional[str] = None

    # ------------------------------------------------------------- telemetry

    def _ensure_telemetry_master(self):
        """The fleet-telemetry plane (monitor/collector.py) needs ONE KV
        endpoint every rank can reach. Multi-node jobs already have it (the
        rendezvous master, exported as PADDLE_CKPT_MASTER); a single-node
        multi-process pod gets a controller-hosted KVServer on a free port,
        exported as PADDLE_MONITOR_MASTER. Best-effort: a bind failure
        degrades to no online aggregation, never to a failed launch."""
        if self.ctx.master or self.ctx.nproc_per_node <= 1 \
                or self._telemetry_ep is not None:
            return
        from .master import KVServer
        try:
            port = free_port()
            srv = KVServer(port, host="127.0.0.1")
            srv.start()
        except OSError:
            return
        self._telemetry_srv = srv
        self._telemetry_ep = f"127.0.0.1:{port}"

    def _stop_telemetry_master(self):
        if self._telemetry_srv is not None:
            try:
                self._telemetry_srv.stop()
            except Exception:
                pass
            self._telemetry_srv = None
            self._telemetry_ep = None

    # -------------------------------------------------------------- preempt

    def _install_stop_handlers(self):
        """Preemption contract: when the CONTROLLER gets SIGTERM/SIGINT, the
        ranks get it immediately (their PreemptionWatcher / AutoCheckpoint
        performs the emergency save), then `ctx.stop_grace` seconds pass
        before the hard kill. Handler work is minimal — forward + flag; the
        poll loop does the draining."""
        if threading.current_thread() is not threading.main_thread():
            return  # tests drive run() off-main; signals stay default there

        self._prev_handlers = {}

        def handler(signum, frame):
            # forward + flag ONLY — no printing: a signal interrupting one
            # of our own stderr writes would make print() a reentrant call
            # into the buffered writer (RuntimeError out of the handler,
            # skipping the very grace window this exists to provide). The
            # drain path logs instead.
            if self._stop_signum is not None:
                return  # already stopping; grace clock keeps running
            self._stop_signum = signum
            # always forward SIGTERM: on an interactive Ctrl-C the terminal
            # already delivered SIGINT to the whole foreground process group
            # (ranks included), and a SECOND SIGINT would escalate the
            # rank's PreemptionWatcher to KeyboardInterrupt mid-emergency-
            # save; SIGTERM just re-records the preemption request
            for p in self.procs:
                if p.poll() is None:
                    try:
                        p.send_signal(signal.SIGTERM)
                    except OSError:
                        pass

        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                self._prev_handlers[s] = signal.signal(s, handler)
        except (ValueError, OSError):
            pass

    def _restore_stop_handlers(self):
        for s, h in getattr(self, "_prev_handlers", {}).items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._prev_handlers = {}

    def _drain_after_stop(self) -> int:
        """Wait out the grace period for ranks to finish their emergency
        checkpoints, then terminate whatever is left. Exit code follows the
        shell convention (128+signum) unless every rank exited cleanly."""
        print(f"[launch] signal {self._stop_signum}: forwarded SIGTERM to "
              f"{len(self.procs)} rank(s); grace "
              f"{self.ctx.stop_grace:.0f}s before kill", file=sys.stderr)
        deadline = time.time() + self.ctx.stop_grace
        while time.time() < deadline:
            if all(p.poll() is not None for p in self.procs):
                break
            time.sleep(0.2)
        self._terminate()
        codes = [p.poll() for p in self.procs]
        if codes and all(c == 0 for c in codes):
            return 0
        return 128 + (self._stop_signum or signal.SIGTERM)

    # ------------------------------------------------------------- rendezvous

    def _rendezvous(self):
        """Returns (node_rank, coordinator host:port).

        Port layout: the --master port P serves the KV store; the jax
        coordinator (inside global rank 0's worker) binds P+1 on the same host
        — a job therefore reserves the (P, P+1) pair. maybe_serve + the P+1
        probe below surface a busy pair early instead of a 300s rendezvous
        timeout against some other job's sockets."""
        ctx = self.ctx
        if ctx.nnodes <= 1:
            return 0, f"127.0.0.1:{free_port()}"
        assert ctx.master, "--master is required when nnodes > 1"
        self._master = Master(ctx.master, ctx.job_id, ctx.nnodes)
        # with explicit ranks only node 0 serves (a non-zero node binding the
        # master port would strand the fleet); with auto ranks, first bind wins
        serving = False
        if ctx.node_rank is None or ctx.node_rank == 0:
            serving = self._master.maybe_serve()
        if ctx.node_rank == 0 and not serving:
            raise RuntimeError(
                f"--node_rank 0 could not bind master {ctx.master}: port busy "
                f"(another job? pick a master port whose P and P+1 are free)")
        host, port = ctx.master.rsplit(":", 1)
        if serving:
            coord_probe = socket.socket()
            try:
                coord_probe.bind(("", int(port) + 1))
            except OSError:
                raise RuntimeError(
                    f"jax coordinator port {int(port) + 1} (master port + 1) "
                    f"is busy; pick a master port with a free successor")
            finally:
                coord_probe.close()
        my_ep = f"{this_host()}:{free_port()}"
        rank, peers = self._master.sync_peers(my_ep, ctx.node_rank)
        return rank, f"{host}:{int(port) + 1}"

    def _bus_token(self, node_rank: int) -> str:
        """A per-job random secret gating the native message bus (see
        core/native/message_bus.cpp security note).

        Single node: generated here, never leaves this process tree. Multi
        node: node 0 generates and publishes it through the rendezvous KV —
        bootstrap-trust, the same model as NCCL-id exchange through a store
        in the reference; export PADDLE_BUS_TOKEN on every node for a fully
        out-of-band secret."""
        # empty counts as unset: a blank env default must not silently
        # disable auth for the whole job
        if os.environ.get("PADDLE_BUS_TOKEN"):
            return os.environ["PADDLE_BUS_TOKEN"]
        if self.ctx.nnodes <= 1 or self._master is None:
            return secrets.token_hex(32)
        key = f"/{self.ctx.job_id}/bus_token"
        client = self._master._client
        if node_rank == 0:
            tok = secrets.token_hex(32)
            if not client.put(key, tok):
                raise RuntimeError("failed to publish bus token to master")
            return tok
        deadline = time.time() + 300
        while True:
            tok = client.get(key)
            if tok:
                return tok
            if time.time() > deadline:
                raise TimeoutError("bus token not published by node 0")
            time.sleep(0.5)

    # ------------------------------------------------------------------ build

    def _build_env(self, node_rank: int, local_rank: int,
                   coordinator: str) -> Dict[str, str]:
        ctx = self.ctx
        nproc = getattr(self, "_np_override", None) or ctx.nproc_per_node
        world = ctx.nnodes * nproc
        rank = node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update(ctx.envs)
        env.update({
            "PADDLE_MASTER": coordinator,
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NNODES": str(ctx.nnodes),
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_JOB_ID": ctx.job_id,
            # per-job message-bus auth secret (rpc/fleet_executor frames
            # carry pickles); generated/shared once per job in _bus_token
            "PADDLE_BUS_TOKEN": self._token,
        })
        if ctx.master:
            # the KV master doubles as the pod-wide checkpoint-commit
            # coordinator (distributed/reshard/commit.py): rank 0 stamps a
            # snapshot's COMMIT only after every rank acked its payload —
            # and as the fleet-telemetry transport (monitor/collector.py
            # falls back to PADDLE_CKPT_MASTER when no dedicated telemetry
            # endpoint is exported)
            env["PADDLE_CKPT_MASTER"] = ctx.master
        if self._telemetry_ep:
            # single-node pods have no rendezvous master; the controller-
            # hosted KVServer carries the /<job>/telemetry/<rank> namespace
            env["PADDLE_MONITOR_MASTER"] = self._telemetry_ep
        if ctx.elastic_level > 0 and ctx.log_dir:
            # ElasticManager's restart wire: a worker that observes a
            # membership change writes the surviving np here and this
            # controller relaunches at that world size
            env["PADDLE_ELASTIC_NP_FILE"] = os.path.join(ctx.log_dir,
                                                         "elastic_np")
        if ctx.devices is not None:
            devices = ctx.devices.split(",")
            if ctx.nproc_per_node > 1:
                # split the visible set across local processes round-robin
                devices = devices[local_rank::ctx.nproc_per_node]
            env["PADDLE_DEVICES"] = ",".join(devices)
            # the actual visibility knob libtpu/jax honor; without it two local
            # processes would race for the same chips (exclusive lock)
            env["TPU_VISIBLE_DEVICES"] = env["PADDLE_DEVICES"]
        return env

    def _spawn(self, node_rank: int, coordinator: str):
        ctx = self.ctx
        nproc = getattr(self, "_np_override", None) or ctx.nproc_per_node
        self.procs, self.logs = [], []
        for local_rank in range(nproc):
            env = self._build_env(node_rank, local_rank, coordinator)
            cmd = [sys.executable] + ctx.script
            log = None
            if ctx.log_dir:
                os.makedirs(ctx.log_dir, exist_ok=True)
                rank = env["PADDLE_TRAINER_ID"]
                log = open(os.path.join(ctx.log_dir, f"workerlog.{rank}"), "ab")
            self.procs.append(subprocess.Popen(
                cmd, env=env, stdout=log or None, stderr=log or None))
            self.logs.append(log)

    # ------------------------------------------------------------------ watch

    def _poll(self) -> Optional[int]:
        """None while all alive; else first non-None returncode (0 only if ALL 0)."""
        codes = [p.poll() for p in self.procs]
        if all(c == 0 for c in codes):
            return 0
        bad = [c for c in codes if c not in (None, 0)]
        if bad:
            return bad[0]
        return None

    def _terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.logs:
            if f:
                f.close()

    # -------------------------------------------------------------- restarts

    def _backoff_sleep(self, fail_streak: int):
        """Exponential backoff + jitter between restarts: an immediately-
        failing pod burns seconds, not its whole restart budget, and a fleet
        of preempted pods doesn't stampede the rendezvous master."""
        base = self.ctx.restart_backoff
        if base <= 0 or fail_streak < 1:
            return
        delay = backoff_delay(fail_streak, base, cap=60.0)
        print(f"[launch] backing off {delay:.1f}s before restart "
              f"(consecutive failures: {fail_streak})", file=sys.stderr)
        deadline = time.time() + delay
        while time.time() < deadline:
            if self._stop_signum is not None:
                return  # a stop signal cancels the pending restart
            time.sleep(min(0.2, max(deadline - time.time(), 0.01)))

    # --------------------------------------------------------------- ps mode

    def _run_ps(self) -> int:
        """PS job: server processes (PADDLE_ROLE=PSERVER at a known port each)
        + trainer processes that see PADDLE_PSERVERS_IP_PORT_LIST. The job
        finishes when every trainer exits; servers are then torn down
        (reference launch/controllers/ps.py semantics)."""
        ctx = self.ctx
        self._token = self._bus_token(0)
        n_srv = ctx.server_num or 1
        n_trn = ctx.trainer_num or 1
        if ctx.nnodes > 1:
            raise ValueError("--run_mode ps currently launches single-node "
                             "jobs (multi-node PS rides --servers lists)")
        ports = [free_port() for _ in range(n_srv)]
        ep_list = ",".join(f"127.0.0.1:{p}" for p in ports)
        servers: List[subprocess.Popen] = []
        trainers: List[subprocess.Popen] = []
        self.logs = []

        def spawn(role, idx, extra):
            env = dict(os.environ)
            env.update(ctx.envs)
            env.update({"PADDLE_ROLE": role, "PADDLE_JOB_ID": ctx.job_id,
                        "PADDLE_PSERVERS_IP_PORT_LIST": ep_list,
                        "PADDLE_TRAINERS_NUM": str(n_trn),
                        "PADDLE_BUS_TOKEN": self._token})
            env.update(extra)
            log = None
            if ctx.log_dir:
                os.makedirs(ctx.log_dir, exist_ok=True)
                log = open(os.path.join(ctx.log_dir,
                                        f"{role.lower()}log.{idx}"), "ab")
            self.logs.append(log)
            return subprocess.Popen([sys.executable] + ctx.script, env=env,
                                    stdout=log or None, stderr=log or None)

        for i, port in enumerate(ports):
            servers.append(spawn("PSERVER", i, {"PADDLE_PSERVER_ID": str(i),
                                                "PADDLE_PORT": str(port)}))
        for i in range(n_trn):
            trainers.append(spawn("TRAINER", i, {"PADDLE_TRAINER_ID": str(i)}))
        self.procs = servers + trainers
        try:
            # poll both roles: a dead pserver fails the job immediately
            # instead of letting trainers hang against a vanished endpoint
            while True:
                if self._stop_signum is not None:
                    return self._drain_after_stop()
                for s in servers:
                    if s.poll() not in (None, 0):
                        return s.poll()
                codes = [t.poll() for t in trainers]
                bad = [c for c in codes if c not in (None, 0)]
                if bad:
                    return bad[0]
                if all(c == 0 for c in codes):
                    return 0
                time.sleep(0.3)
        finally:
            self._terminate()  # also closes self.logs

    def run_elastic(self) -> int:
        """Elastic supervision (reference: fleet/elastic/manager.py:252-321 —
        on node loss the manager rewrites PADDLE_TRAINER_ENDPOINTS and
        relaunches trainers at the surviving world size).

        Single-node semantics here: a dead worker scales the world IN
        (np-1, down to --min_np); a control file `<log_dir>/elastic_np`
        containing a larger np scales it OUT at the next boundary. Every
        incarnation gets a FRESH coordinator (the old jax.distributed world
        is unsalvageable once a member died) and fresh PADDLE_* envs; workers
        are expected to resume from their own checkpoints — on TPU pods
        checkpoint-restore is the preemption story, not live endpoint rewrite
        (slices restore whole; see ElasticManager docstring)."""
        ctx = self.ctx
        if ctx.nnodes > 1:
            raise ValueError("elastic_level=1 supervises a single node's "
                             "workers (multi-node worlds restore from "
                             "checkpoint via the watcher + rendezvous)")
        np_now = ctx.nproc_per_node
        incarnation = 0
        ctl = os.path.join(ctx.log_dir, "elastic_np") if ctx.log_dir else None

        np_max = ctx.max_np or ctx.nproc_per_node
        # a deterministically-failing script must not restart forever: with
        # --max_restart unset, elastic still stops after a default budget
        budget = ctx.max_restart if ctx.max_restart > 0 else 10
        fail_streak = 0
        # one telemetry endpoint across incarnations: a restarted rank's new
        # incarnation lands in the same fleet stream
        self._ensure_telemetry_master()

        def desired_np():
            if ctl:
                try:
                    with open(ctl) as f:
                        want = int(f.read().strip())
                    return max(ctx.min_np, min(want, np_max))
                except (OSError, ValueError):
                    pass
            return None

        try:
            while True:
                if self._stop_signum is not None:
                    return self._drain_after_stop()
                self._np_override = np_now
                coordinator = f"127.0.0.1:{free_port()}"
                self._token = self._bus_token(0)
                os.environ["PADDLE_ELASTIC_RESTART"] = str(incarnation)
                ctx.envs["PADDLE_ELASTIC_RESTART"] = str(incarnation)
                self._spawn(0, coordinator)
                t_up = time.time()
                rc = None
                while rc is None:
                    if self._stop_signum is not None:
                        return self._drain_after_stop()
                    time.sleep(0.3)
                    rc = self._poll()
                    want = desired_np()
                    if rc is None and want is not None and want != np_now:
                        # scale-out (operator control file) or scale-in
                        # (ElasticManager announced a smaller surviving
                        # world): restart the pod at the requested np; the
                        # workers resume from their pod-committed
                        # checkpoint, resharded onto the new world size
                        direction = "OUT" if want > np_now else "IN"
                        print(f"[launch] elastic scale-{direction} "
                              f"requested: {np_now} -> {want}",
                              file=sys.stderr)
                        self._terminate()
                        np_now = want
                        incarnation += 1
                        fail_streak = 0  # requested, not a failure
                        break
                else:
                    self._terminate()
                    if rc == 0:
                        return 0
                    if incarnation >= budget:
                        print(f"[launch] elastic: restart budget "
                              f"({budget}) exhausted", file=sys.stderr)
                        return rc
                    if np_now - 1 >= ctx.min_np:
                        print(f"[launch] worker lost (rc={rc}); elastic "
                              f"scale-IN {np_now} -> {np_now - 1}",
                              file=sys.stderr)
                        np_now -= 1
                    else:
                        print(f"[launch] worker lost (rc={rc}) at the "
                              f"--min_np floor; restarting at np={np_now}",
                              file=sys.stderr)
                    incarnation += 1
                    # an incarnation that ran a while earned a fresh backoff
                    # ladder; a crash-on-startup climbs it
                    fail_streak = 1 if time.time() - t_up >= 60.0 \
                        else fail_streak + 1
                    self._backoff_sleep(fail_streak)
                continue
        finally:
            self._terminate()
            self._stop_telemetry_master()

    def run(self) -> int:
        # the controller IS a preemption relay: hosted controllers run() on
        # the main thread, so signal handlers install here and restore on
        # exit (pytest-hosted controllers must not leak them)
        self._install_stop_handlers()
        try:
            return self._run()
        finally:
            self._restore_stop_handlers()

    def _run(self) -> int:
        if self.ctx.run_mode == "ps":
            return self._run_ps()
        if self.ctx.elastic_level > 0:
            return self.run_elastic()
        if self.ctx.max_restart > 0 and self.ctx.nnodes > 1:
            # a local-pod restart would re-register a dead incarnation with the
            # still-live jax coordinator and hang the fleet; whole-job restart
            # needs master-coordinated teardown (reference elastic manager)
            raise ValueError("--max_restart is only supported for single-node "
                             "jobs (nnodes == 1)")
        node_rank, coordinator = self._rendezvous()
        self._token = self._bus_token(node_rank)
        self._ensure_telemetry_master()
        restarts = 0
        fail_streak = 0
        try:
            while True:
                self._spawn(node_rank, coordinator)
                t_up = time.time()
                rc = None
                while rc is None:
                    if self._stop_signum is not None:
                        return self._drain_after_stop()
                    time.sleep(0.5)
                    rc = self._poll()
                self._terminate()
                if rc == 0 or restarts >= self.ctx.max_restart:
                    return rc
                restarts += 1
                print(f"[launch] pod failed (rc={rc}); restart "
                      f"{restarts}/{self.ctx.max_restart}", file=sys.stderr)
                fail_streak = 1 if time.time() - t_up >= 60.0 \
                    else fail_streak + 1
                self._backoff_sleep(fail_streak)
                if self._stop_signum is not None:
                    return self._drain_after_stop()
        finally:
            self._terminate()
            self._stop_telemetry_master()
            if self._master is not None:
                self._master.stop()
