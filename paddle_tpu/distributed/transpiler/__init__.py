"""DistributeTranspiler: parameter-server program rewriting (legacy PS mode).

Reference analog: python/paddle/distributed/transpiler/distribute_transpiler.py
— rewrites a training program so each trainer sends grads to / recvs params
from parameter servers (dense blocks sliced across pservers, optionally
geo-SGD async), and get_pserver_program builds each server's half.

TPU-native redesign: there is no program surgery — the model stays a Layer and
trains on-device; the transpiler's real job (partition parameters over server
endpoints + give both sides their runtime) maps to table assignments over the
native-TCPStore PS (distributed/ps). Sync mode pulls before forward and pushes
after backward every step; geo mode pushes accumulated deltas every K steps
(reference geo-SGD).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DistributeTranspilerConfig", "DistributeTranspiler"]


class DistributeTranspilerConfig:
    """reference DistributeTranspilerConfig (slice/geo knobs)."""

    def __init__(self):
        self.slice_var_up = True
        self.min_block_size = 8192
        self.mode = "sync"          # "sync" | "geo"
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._assign: Dict[str, int] = {}     # param name -> pserver index
        self._endpoints: List[str] = []
        self._model = None
        self._trainers = 1
        self._trainer_id = 0

    def transpile(self, trainer_id: int, program=None, pservers: str = "",
                  trainers: int = 1, sync_mode: bool = True):
        """`program` is the model Layer (the trace IS the program here);
        pservers: comma-separated host:port list."""
        self._trainer_id = trainer_id
        self._model = program
        self._trainers = trainers
        self._endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        if not self._endpoints:
            raise ValueError("transpile needs at least one pserver endpoint")
        if not sync_mode:
            self.config.mode = "geo"
        # greedy size-balanced assignment (reference slice_var_up splits big
        # vars; table-granularity assignment keeps each param whole — the
        # TCPStore transport has no block-slicing benefit)
        sizes = [(name, int(np.prod(p.shape)))
                 for name, p in program.named_parameters()]
        load = [0] * len(self._endpoints)
        for name, sz in sorted(sizes, key=lambda kv: -kv[1]):
            i = load.index(min(load))
            self._assign[name] = i
            load[i] += sz
        return self

    # ------------------------------------------------------------- pserver

    def get_pserver_program(self, endpoint: str):
        """Table specs this endpoint serves: {param name: shape} — feed into
        ps.DenseTable/PSServer (reference returns the server ProgramDesc)."""
        idx = self._endpoints.index(endpoint)
        return {name: tuple(p.shape)
                for name, p in self._model.named_parameters()
                if self._assign[name] == idx}

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint), None  # (main, startup)

    # ------------------------------------------------------------- trainer

    def get_trainer_program(self) -> "TrainerProgram":
        return TrainerProgram(self)


class TrainerProgram:
    """Trainer-side runtime: pull params from their pservers before forward,
    push grads after backward (reference send/recv op insertion)."""

    def __init__(self, t: DistributeTranspiler):
        from ..ps import PSClient
        self._t = t
        self._clients = []
        for ep in t._endpoints:
            host, port = ep.rsplit(":", 1)
            self._clients.append(PSClient(host, int(port)))
        self._geo_acc: Dict[str, np.ndarray] = {}
        self._step = 0

    def pull_params(self):
        model, t = self._t._model, self._t
        for name, p in model.named_parameters():
            cli = self._clients[t._assign[name]]
            flat = cli.pull_dense(name)
            p.set_value(flat.reshape(tuple(p.shape)).astype(str(p.dtype)))

    def push_grads(self, lr: float = 1.0):
        """Sync mode: push raw grads (server applies its optimizer). Geo mode:
        accumulate locally, push deltas every geo_sgd_need_push_nums steps."""
        model, t = self._t._model, self._t
        cfg = t.config
        self._step += 1
        for name, p in model.named_parameters():
            if p.grad is None:
                continue
            g = np.asarray(p.grad.numpy(), np.float32).ravel()
            if cfg.mode == "geo":
                acc = self._geo_acc.setdefault(name, np.zeros_like(g))
                acc += g
                if self._step % cfg.geo_sgd_need_push_nums == 0:
                    self._clients[t._assign[name]].push_dense(name, acc * lr)
                    acc[:] = 0
            else:
                self._clients[t._assign[name]].push_dense(name, g * lr)
