"""group_sharded_parallel — ZeRO stages over the mesh.

Reference analog: python/paddle/distributed/sharding/group_sharded.py:37 dispatching to
GroupShardedOptimizerStage2 / GroupShardedStage2 / GroupShardedStage3
(fleet/meta_parallel/sharding/, 632/669/1117 LoC of bucketing, hooks and
gather/release bookkeeping).

TPU-native mapping (SURVEY.md §7 stage 7):
  os    (stage 1): optimizer states sharded over the "sharding" axis
  os_g  (stage 2): + gradients resharded onto the axis as they accumulate
  p_g_os(stage 3): + parameters stored sharded; XLA all-gathers them where used
                   inside each compiled op and frees the gathered copy after —
                   buffer donation + scheduling play the role of the reference's
                   explicit allgather-on-use / release-after hooks.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ..env import get_mesh
from ..fleet.meta_optimizers import (DygraphShardingOptimizer, _existing_spec,
                                     _shard_spec_for)
from ..fleet.meta_parallel.wrappers import InnerLayerDelegate


class _GroupShardedModel(InnerLayerDelegate, Layer):
    def __init__(self, layer: Layer, level: str, group=None, offload=False):
        super().__init__()
        self._layers = layer
        self._level = level
        mesh = get_mesh()
        self._axis_size = mesh.shape.get("sharding", 1) if mesh is not None else 1
        if self._axis_size > 1:
            if level == "p_g_os":
                self._shard_params(mesh)
            if level in ("os_g", "p_g_os"):
                self._mark_grad_shardings(mesh)

    def _shard_params(self, mesh):
        # compose with any existing placement (e.g. TP's "model" axis): the
        # sharding axis takes the largest still-free divisible dim
        for _, p in self._layers.named_parameters():
            spec = _shard_spec_for(tuple(p.shape), mesh.shape["sharding"],
                                   _existing_spec(p.value()))
            p._data = jax.device_put(p.value(), NamedSharding(mesh, spec))

    def _mark_grad_shardings(self, mesh):
        # stage >= 2: gradients are sharded AT tape accumulation (see
        # Tensor._accumulate_grad) — they never sit replicated between
        # backward and step, which is the entire point of os_g
        for _, p in self._layers.named_parameters():
            spec = _shard_spec_for(tuple(p.shape), mesh.shape["sharding"],
                                   _existing_spec(p.value()))
            p._grad_sharding = NamedSharding(mesh, spec)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)



class _ShardingStage2Optimizer(DygraphShardingOptimizer):
    """Stage 2/3 optimizer: states sharded (stage 1) + a grad-sharding contract.

    Eager grads are already sharded at accumulation (_mark_grad_shardings);
    `_grad_spec` additionally lets TrainStep compile the same semantics in as
    `with_sharding_constraint` on the grads — XLA then emits reduce-scatter at
    grad production instead of all-reduce + late reshard."""

    def __init__(self, optimizer, hcg=None, strategy=None, offload=False,
                 grad_bucket_bytes=None):
        super().__init__(optimizer, hcg, strategy, offload=offload,
                         grad_bucket_bytes=grad_bucket_bytes)
        # the fleet strategy route (sharding_configs stage>=2) wraps ONLY the
        # optimizer — no _GroupShardedModel around the layer to mark the
        # tape — so the stage-2 contract is enforced here too: grads shard
        # AT accumulation, never sitting replicated between backward and
        # step. group_sharded_parallel's model wrapper already marked these
        # (identical specs); don't overwrite an existing mark.
        mesh = get_mesh()
        if mesh is not None and mesh.shape.get("sharding", 1) > 1:
            for p in self._inner_opt._parameter_list:
                if getattr(p, "_grad_sharding", None) is None:
                    spec = _shard_spec_for(tuple(p.shape),
                                           mesh.shape["sharding"],
                                           _existing_spec(p.value()))
                    p._grad_sharding = NamedSharding(mesh, spec)

    def _grad_spec(self, p):
        mesh = get_mesh()
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return None
        spec = _shard_spec_for(tuple(p.shape), mesh.shape["sharding"],
                               _existing_spec(p.value()))
        return NamedSharding(mesh, spec)

    def step(self):
        mesh = get_mesh()
        if mesh is not None and mesh.shape.get("sharding", 1) > 1:
            # safety net for grads produced outside the marked tape path
            from ...core.lazy import lazy_device_put
            for p in self._inner_opt._parameter_list:
                if p._grad is not None and \
                        getattr(p, "_grad_sharding", None) is not None:
                    p._grad = lazy_device_put(p._grad, p._grad_sharding)
        return super().step()


def group_sharded_parallel(model: Layer, optimizer, level: str = "os",
                           scaler=None, group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size: int = 2 ** 23,
                           segment_size: int = 2 ** 20, sync_comm: bool = False,
                           grad_bucket_bytes: Optional[int] = None):
    """reference group_sharded.py:37: returns (model, optimizer, scaler).

    ``grad_bucket_bytes`` (the compiled path's collective-coalescing knob):
    jit.TrainStep fuses per-microbatch grad reduce-scatters smaller than
    this into flat fused buckets — fewer, larger collectives for meshes
    where per-collective launch latency dominates. Default None/0 keeps one
    shard constraint per parameter, which XLA already schedules/fuses well
    and which avoids the bucket's flat-layout reshard (measurably cheaper
    on the CPU mesh). ``buffer_max_size`` (the reference eager-hook bucket
    cap) is accepted for parity; the compiled path only buckets when
    ``grad_bucket_bytes`` asks for it."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os | os_g | p_g_os, got {level!r}")
    bucket = grad_bucket_bytes
    wrapped_model = _GroupShardedModel(model, level, group, offload)
    if level == "os":
        wrapped_opt = DygraphShardingOptimizer(optimizer, offload=offload,
                                               grad_bucket_bytes=bucket)
    else:
        wrapped_opt = _ShardingStage2Optimizer(optimizer, offload=offload,
                                               grad_bucket_bytes=bucket)
    return wrapped_model, wrapped_opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference save_group_sharded_model: persist the full (unsharded) state."""
    import os

    from ... import framework
    target = model._layers if isinstance(model, _GroupShardedModel) else model
    os.makedirs(output, exist_ok=True)
    framework.io.save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        inner = getattr(optimizer, "_inner_opt", optimizer)
        framework.io.save(inner.state_dict(),
                          os.path.join(output, "model.pdopt"))
