"""Elastic resharding: resume training across world sizes.

Reference analog: the fleet layer treats world size as a job-LIFETIME
variable (HybridCommunicateGroup + elastic launch) — preempted pods come
back smaller or larger and training continues. This package closes that
loop for the checkpoint path:

* :mod:`snapshot` — per-shard payloads: each rank persists only its
  host-addressable blocks, under a rank-indexed block map recording every
  array's global shape, sharding spec, and tiling;
* :mod:`plan` — the N→M geometry: byte-identical N→N fast path,
  index-mapped reads when shard boundaries nest, gather-then-re-place
  fallback otherwise;
* :mod:`commit` — pod-wide commit over the launcher's KV master: rank 0
  stamps the COMMIT manifest only after every rank acked a durable payload,
  so a multi-host snapshot is atomic fleet-wide.

``distributed/checkpoint.py`` routes through this package automatically:
saves of sharded state write the per-shard format, and
``load_checkpoint``/``AutoCheckpoint``/``TrainStep.load_checkpoint``
transparently reshard an N-way snapshot onto the current mesh.
"""
from .commit import PodCommit, PodCommitError, from_env as pod_commit_from_env
from .plan import ReshardPlan, classify, normalize_index, target_indices
from .snapshot import (PartialSnapshotError, ReshardStats, StagedArray,
                       coverage_problems, flatten_state, is_sharded_array,
                       load_sharded, read_index, save_sharded, stage,
                       unflatten_state)

__all__ = ["PodCommit", "PodCommitError", "pod_commit_from_env",
           "ReshardPlan", "classify", "normalize_index", "target_indices",
           "PartialSnapshotError", "ReshardStats", "StagedArray",
           "coverage_problems", "flatten_state", "is_sharded_array",
           "load_sharded", "read_index", "save_sharded", "stage",
           "unflatten_state"]
