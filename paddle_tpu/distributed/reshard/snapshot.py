"""Per-shard checkpoint payloads with a rank-indexed block map.

Reference analog: GroupSharded save paths — every rank persists the shards
it owns, and a manifest records how they tile each global array. This is the
on-disk format the elastic resume path reshards from:

    <payload>.shards/
        index.rank<r>.json      one per writing rank (schema below)
        skeleton.pkl            rank 0: the state structure with arrays
                                replaced by {"__reshard_array__": <key>}
        rank_<r>/a<i>_b<j>.bin  raw C-order bytes of one block

Index schema (per rank)::

    {"schema": 1, "rank": r,
     "arrays": {<key>: {
         "shape": [...], "dtype": "float32",
         "spec": [null, "sharding", ["data", "model"], ...],   # per dim
         "mesh": {"data": 2, "sharding": 4},                   # axis sizes
         "blocks": [{"file": "rank_0/a0_b0.bin",
                     "index": [[0, 8], [0, 16]]}],              # MY blocks
         "all_blocks": [{"index": [[0, 8], [0, 16]], "owner": 0}, ...]}}}

``all_blocks`` is the full tiling every rank can compute from the array's
global sharding metadata; ``blocks`` are the ones THIS rank persisted. A
snapshot whose union of present blocks does not cover ``all_blocks`` is
PARTIAL — ``tools/ckpt_inspect.py`` flags it and :func:`load_sharded`
refuses it (a rank's payload never landed).

Keys are JSON-encoded paths into the (nested) state structure, so array
names may contain any character. Non-array leaves (step counters, LR
scheduler state) ride in the rank-0 skeleton pickle.

Raw ``.bin`` blocks instead of ``.npy``: extended dtypes (bfloat16) do not
survive ``np.save``, and a headerless block is byte-comparable across
worlds — the N→N fast path's "byte-identical" contract is literal.
"""
from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .plan import Index, ReshardPlan, normalize_index, target_indices

__all__ = ["StagedArray", "stage", "is_sharded_array", "flatten_state",
           "unflatten_state", "save_sharded", "load_sharded", "read_index",
           "coverage_problems", "ReshardStats", "SCHEMA_VERSION",
           "encode_block", "decode_block", "read_block"]

SCHEMA_VERSION = 1
_MARKER = "__reshard_array__"


class PartialSnapshotError(ValueError):
    """The present rank payloads do not cover the block index map — a
    rank's shards never landed (or were lost). Distinct from a template
    shape mismatch: resume treats PARTIAL like a torn save (skip and fall
    back), while a snapshot that does not FIT the network must stay a loud
    error."""


def _dtype_name(dt) -> str:
    return np.dtype(dt).name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax ships it; bfloat16/float8 live here
        return np.dtype(getattr(ml_dtypes, name))


def _spec_json(sharding) -> Tuple[Optional[list], Dict[str, int]]:
    """(per-dim spec, mesh axis sizes) of a NamedSharding, JSON-ready."""
    from jax.sharding import NamedSharding
    if not isinstance(sharding, NamedSharding):
        return None, {}
    spec = []
    for s in tuple(sharding.spec):
        spec.append(list(s) if isinstance(s, tuple) else s)
    return spec, {str(k): int(v) for k, v in sharding.mesh.shape.items()}


class StagedArray:
    """One array staged to host as per-shard numpy blocks.

    This is what :func:`paddle_tpu.distributed.checkpoint._host_copy` now
    produces for sharded arrays: only the shards THIS process can address
    are copied (``blocks``), never the assembled global array — the PR 4
    carve-out where non-fully-addressable arrays kept live jax references
    is closed by construction. ``all_blocks`` (index -> owner rank) is the
    global tiling used for the manifest's coverage map."""

    def __init__(self, shape, dtype_name: str, spec, mesh_axes,
                 blocks: Dict[Index, np.ndarray],
                 all_blocks: Dict[Index, int]):
        self.shape = tuple(int(s) for s in shape)
        self.dtype_name = dtype_name
        self.spec = spec
        self.mesh_axes = dict(mesh_axes)
        self.blocks = blocks          # index -> numpy payload (host copies)
        self.all_blocks = all_blocks  # index -> owner rank

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.blocks.values())


def is_sharded_array(a) -> bool:
    """True when ``a`` must go through the per-shard format: it spans
    devices this process cannot address, or its NamedSharding actually
    splits a dimension (a mesh-replicated array is neither)."""
    import jax
    from jax.sharding import NamedSharding
    if not isinstance(a, jax.Array):
        return False
    if not getattr(a, "is_fully_addressable", True):
        return True
    sh = getattr(a, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return False
    for s in tuple(sh.spec):
        axes = s if isinstance(s, tuple) else ((s,) if s is not None else ())
        for ax in axes:
            if sh.mesh.shape.get(ax, 1) > 1:
                return True
    return False


def stage(a, rank: Optional[int] = None) -> StagedArray:
    """Host-stage a jax array per shard. Each distinct shard region is
    copied once; regions replicated across processes are owned by the
    lowest process index holding them (that rank persists the bytes)."""
    import jax
    if rank is None:
        rank = jax.process_index()
    shape = tuple(a.shape)
    spec, mesh_axes = _spec_json(getattr(a, "sharding", None))
    owners: Dict[Index, int] = {}
    sh = getattr(a, "sharding", None)
    if sh is not None:
        for dev, raw in sh.devices_indices_map(shape).items():
            idx = normalize_index(raw, shape)
            proc = getattr(dev, "process_index", 0)
            if idx not in owners or proc < owners[idx]:
                owners[idx] = proc
    else:
        owners[normalize_index(None, shape)] = rank
    blocks: Dict[Index, np.ndarray] = {}
    for shard in getattr(a, "addressable_shards", ()):
        idx = normalize_index(shard.index, shape)
        if owners.get(idx, rank) == rank and idx not in blocks:
            blocks[idx] = np.ascontiguousarray(np.asarray(shard.data))
    if not blocks and owners and rank in owners.values():
        # no .addressable_shards (plain numpy fed through): whole array
        blocks[normalize_index(None, shape)] = np.ascontiguousarray(
            np.asarray(a))
    return StagedArray(shape, _dtype_name(a.dtype), spec, mesh_axes,
                       blocks, owners)


# -------------------------------------------------------------- state walking

def _is_array_leaf(v) -> bool:
    import jax
    from ...core.tensor import Tensor
    return isinstance(v, (jax.Array, np.ndarray, Tensor, StagedArray))


def flatten_state(state) -> Tuple[Dict[str, Any], Any]:
    """(flat arrays keyed by JSON path, skeleton with markers). The skeleton
    preserves every non-array leaf (ints, floats, scheduler dicts) in
    place."""
    flat: Dict[str, Any] = {}

    def walk(obj, path):
        from ...core.tensor import Tensor
        if isinstance(obj, Tensor):
            obj = obj.value()
        if _is_array_leaf(obj):
            key = json.dumps(path)
            flat[key] = obj
            return {_MARKER: key}
        if isinstance(obj, dict):
            return {k: walk(v, path + [str(k)]) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [walk(v, path + [i]) for i, v in enumerate(obj)]
            return out if isinstance(obj, list) else tuple(out)
        return obj

    skeleton = walk(state, [])
    return flat, skeleton


def unflatten_state(skeleton, flat: Dict[str, Any]):
    if isinstance(skeleton, dict):
        if set(skeleton) == {_MARKER}:
            return flat[skeleton[_MARKER]]
        return {k: unflatten_state(v, flat) for k, v in skeleton.items()}
    if isinstance(skeleton, (list, tuple)):
        out = [unflatten_state(v, flat) for v in skeleton]
        return out if isinstance(skeleton, list) else tuple(out)
    return skeleton


# --------------------------------------------------------------------- saving

def save_sharded(path: str, state, rank: int = 0,
                 write_skeleton: Optional[bool] = None) -> Dict[str, Any]:
    """Write this rank's blocks + index under ``path``. Returns a summary
    ({"files": n, "bytes": n}) for the pod-commit ack. The skeleton (the
    state structure around the arrays) is written when ``write_skeleton``
    (default: rank 0 — pod mode's lead writer; per-rank-private directories
    pass True so each directory is self-contained)."""
    os.makedirs(path, exist_ok=True)
    rank_dir = os.path.join(path, f"rank_{rank}")
    os.makedirs(rank_dir, exist_ok=True)
    flat, skeleton = flatten_state(state)
    index = {"schema": SCHEMA_VERSION, "rank": int(rank), "arrays": {}}
    files = 0
    total = 0
    for i, (key, val) in enumerate(flat.items()):
        staged = val if isinstance(val, StagedArray) else stage(val, rank)
        entry = {"shape": list(staged.shape), "dtype": staged.dtype_name,
                 "spec": staged.spec,
                 "mesh": staged.mesh_axes,
                 "blocks": [],
                 "all_blocks": [{"index": [list(ab) for ab in idx],
                                 "owner": owner}
                                for idx, owner in sorted(
                                    staged.all_blocks.items())]}
        for j, (idx, data) in enumerate(sorted(staged.blocks.items())):
            rel = f"rank_{rank}/a{i}_b{j}.bin"
            with open(os.path.join(path, rel), "wb") as f:
                f.write(np.ascontiguousarray(data).tobytes())
            entry["blocks"].append({"file": rel,
                                    "index": [list(ab) for ab in idx]})
            files += 1
            total += data.nbytes
        index["arrays"][key] = entry
    if write_skeleton if write_skeleton is not None else rank == 0:
        from ... import framework
        framework.io.save(skeleton, os.path.join(path, "skeleton.pkl"))
        files += 1
    with open(os.path.join(path, f"index.rank{rank}.json"), "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)
    files += 1
    return {"files": files, "bytes": total}


# ---------------------------------------------------------- block wire format

def encode_block(arr) -> Tuple[bytes, Dict[str, Any]]:
    """Raw C-order bytes + JSON-ready meta for ONE host array — the same
    headerless ``.bin`` contract :func:`save_sharded` writes to disk, as an
    in-memory pair. This is the KV block pool's wire format
    (``serving/kvpool.py``): bfloat16-safe (``np.save`` is not) and
    byte-comparable across processes, so a pool round-trip is bitwise."""
    a = np.ascontiguousarray(np.asarray(arr))
    return a.tobytes(), {"shape": [int(s) for s in a.shape],
                         "dtype": _dtype_name(a.dtype)}


def decode_block(data: bytes, meta: Dict[str, Any]) -> np.ndarray:
    """Inverse of :func:`encode_block`. Size-validates against the meta
    (``prod(()) == 1`` covers scalars) so a truncated or mis-keyed payload
    raises instead of reshaping garbage into the KV cache."""
    shape = tuple(int(s) for s in meta["shape"])
    dtype = _resolve_dtype(meta["dtype"])
    want = dtype.itemsize * int(math.prod(shape))
    if len(data) != want:
        raise ValueError(f"block payload is {len(data)} bytes, expected "
                         f"{want} for shape {shape} dtype {meta['dtype']}")
    return np.frombuffer(data, dtype=dtype).reshape(shape)


def read_block(path: str, key: str, block_index,
               index: Optional[Dict[str, Any]] = None) -> np.ndarray:
    """Read ONE block of one array out of a sharded payload without
    assembling the array (block-granular entry point; ``load_sharded``
    reads whole arrays). ``block_index`` is the normalized per-dim
    ``[[a, b], ...]`` region as recorded in the rank index; pass a
    pre-merged ``read_index(path)`` result to amortize the index scan over
    many block reads. Raises ``KeyError`` when the array or block is not
    present in any rank's payload."""
    if index is None:
        index = read_index(path)
    entry = index["arrays"].get(key)
    if entry is None:
        raise KeyError(f"{path}: no array {key!r} in index")
    idx = tuple(tuple(int(x) for x in ab) for ab in block_index)
    rel = _entry_indices(entry).get(idx)
    if rel is None:
        raise KeyError(f"{path}: {key!r} has no block {idx}")
    dtype = _resolve_dtype(entry["dtype"])
    return np.asarray(_make_reader(os.path.join(path, rel), dtype, idx)())


# -------------------------------------------------------------------- loading

def read_index(path: str) -> Dict[str, Any]:
    """Merge every rank's index under ``path``: {key: meta + present blocks}.
    Raises FileNotFoundError when no index exists (not a sharded payload)."""
    ranks = []
    merged: Dict[str, Any] = {}
    for name in sorted(os.listdir(path)):
        if not (name.startswith("index.rank") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(path, name)) as f:
                idx = json.load(f)
        except (OSError, ValueError):
            # a rotted rank index reads as that rank's payload missing:
            # coverage flags the gap (PARTIAL) and auto-resume falls back,
            # instead of a raw JSONDecodeError crashing the resume scan
            continue
        ranks.append(int(idx.get("rank", 0)))
        for key, entry in idx.get("arrays", {}).items():
            tgt = merged.setdefault(key, {"shape": entry["shape"],
                                          "dtype": entry["dtype"],
                                          "spec": entry.get("spec"),
                                          "mesh": entry.get("mesh", {}),
                                          "blocks": [],
                                          "all_blocks":
                                              entry.get("all_blocks", [])})
            tgt["blocks"].extend(entry.get("blocks", []))
    if not merged and not ranks:
        raise FileNotFoundError(f"{path}: no index.rank*.json")
    return {"ranks": sorted(set(ranks)), "arrays": merged}


def _entry_indices(entry) -> Dict[Index, str]:
    return {tuple(tuple(ab) for ab in b["index"]): b["file"]
            for b in entry["blocks"]}


def coverage_problems(index: Dict[str, Any], path: Optional[str] = None
                      ) -> List[str]:
    """PARTIAL detection: every ``all_blocks`` region must have a present
    block (and, when ``path`` is given, a file of the right size)."""
    problems = []
    for key, entry in sorted(index["arrays"].items()):
        present = _entry_indices(entry)
        itemsize = _resolve_dtype(entry["dtype"]).itemsize
        for ab in entry["all_blocks"]:
            idx = tuple(tuple(x) for x in ab["index"])
            rel = present.get(idx)
            if rel is None:
                problems.append(
                    f"{key}: block {idx} (owner rank {ab.get('owner')}) "
                    f"missing — rank payload never landed")
                continue
            if path is not None:
                p = os.path.join(path, rel)
                # prod(()) == 1 covers scalars; a genuinely zero-size dim
                # means a legitimately 0-byte block — no `or 1` fudge, or
                # every snapshot holding an empty array self-rejects
                want = itemsize * int(math.prod(b - a for a, b in idx))
                if not os.path.isfile(p):
                    problems.append(f"{key}: {rel} missing on disk")
                elif os.path.getsize(p) != want:
                    problems.append(f"{key}: {rel} is "
                                    f"{os.path.getsize(p)} bytes, expected "
                                    f"{want}")
    return problems


def _src_world(entry) -> int:
    """Sharded degree of one saved array: product of mesh axis sizes its
    spec actually uses (1 for replicated/unsharded)."""
    world = 1
    mesh = entry.get("mesh") or {}
    seen = set()
    for s in entry.get("spec") or []:
        axes = s if isinstance(s, list) else ([s] if s is not None else [])
        for ax in axes:
            if ax not in seen:
                seen.add(ax)
                world *= int(mesh.get(ax, 1))
    return world


class ReshardStats:
    """What a sharded load did, for the reshard/* gauges."""

    def __init__(self):
        self.arrays = 0
        self.identity = 0
        self.mapped = 0
        self.gathered = 0
        self.nestable_gather = 0
        self.bytes_read = 0
        self.src_world = 1
        self.dst_world = 1
        self.wall_s = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return dict(vars(self))


def _nestable(n: int, m: int) -> bool:
    return n > 0 and m > 0 and (n % m == 0 or m % n == 0)


def load_sharded(path: str, template: Optional[Dict[str, Any]] = None,
                 partial_ok: bool = False, force_gather: bool = False
                 ) -> Tuple[Dict[str, Any], Any, ReshardStats]:
    """Load a sharded payload, resharding onto the template's placements.

    ``template``: flat {json-path-key: array-with-target-sharding} (from
    :func:`flatten_state` over the live state). Keys absent from the
    template load to host numpy; template keys absent from the snapshot are
    ignored (the caller decides whether that is an error). Returns
    ``(flat arrays, skeleton, stats)``; shape mismatches raise ValueError
    naming the key (restoring through a mismatched template would silently
    truncate — the load_state_dict contract). ``force_gather`` routes every
    array through the gather fallback — the trivially-correct path the
    index-mapped reader is tested against."""
    import time
    t0 = time.perf_counter()
    index = read_index(path)
    problems = coverage_problems(index, path)
    if problems and not partial_ok:
        raise PartialSnapshotError(
            f"{path}: PARTIAL sharded snapshot — " + "; ".join(problems[:4])
            + (f" (+{len(problems) - 4} more)"
               if len(problems) > 4 else ""))
    from ... import framework
    skel_path = os.path.join(path, "skeleton.pkl")
    try:
        skeleton = framework.io.load(skel_path) \
            if os.path.exists(skel_path) else None
    except Exception as e:
        # a rotted skeleton is the same class of fault as a lost rank
        # payload: resume must fall back past it, not crash on unpickling
        raise PartialSnapshotError(
            f"{path}: skeleton.pkl unreadable ({type(e).__name__}: {e})")
    stats = ReshardStats()
    template = template or {}
    out: Dict[str, Any] = {}
    for key, entry in index["arrays"].items():
        shape = tuple(entry["shape"])
        dtype = _resolve_dtype(entry["dtype"])
        tmpl = template.get(key)
        if tmpl is not None:
            t_arr = tmpl
            from ...core.tensor import Tensor
            if isinstance(t_arr, Tensor):
                t_arr = t_arr.value()
            if tuple(t_arr.shape) != shape:
                raise ValueError(
                    f"reshard load: {json.loads(key)!r} is "
                    f"{tuple(t_arr.shape)} in this run but {shape} in the "
                    f"checkpoint ({path}) — the snapshot does not fit")
            sharding = getattr(t_arr, "sharding", None)
        else:
            sharding = None
        blocks = {}
        for idx, rel in _entry_indices(entry).items():
            p = os.path.join(path, rel)
            if os.path.isfile(p):
                blocks[idx] = _make_reader(p, dtype, idx)
        want = {tuple(tuple(x) for x in ab["index"])
                for ab in entry["all_blocks"]}
        if not want <= set(blocks):
            # only reachable under partial_ok (coverage raised above
            # otherwise): salvage whole arrays, skip the torn one
            continue
        plan = ReshardPlan(shape, dtype, blocks,
                           target_indices(sharding, shape))
        if force_gather and plan.kind != "identity":
            plan.kind = "gather"
        out[key] = plan.place(sharding)
        stats.arrays += 1
        stats.bytes_read += plan.bytes_read
        src_w = _src_world(entry)
        stats.src_world = max(stats.src_world, src_w)
        dst_w = len(plan.dst_indices)
        stats.dst_world = max(stats.dst_world, dst_w)
        if plan.kind == "identity":
            stats.identity += 1
        elif plan.kind == "mapped":
            stats.mapped += 1
        else:
            stats.gathered += 1
            if _nestable(src_w, dst_w) and not force_gather:
                stats.nestable_gather += 1
    stats.wall_s = time.perf_counter() - t0
    return out, skeleton, stats


def _make_reader(path: str, dtype: np.dtype, idx: Index):
    shape = tuple(b - a for a, b in idx)

    def read() -> np.ndarray:
        if not shape or 0 in shape:
            # scalars and zero-size blocks: mmap rejects empty files
            return np.fromfile(path, dtype=dtype).reshape(shape)
        # memmap, not fromfile: an index-mapped load slices only its own
        # regions out of each block, and the OS pages in just those bytes —
        # a 1->M scale-out must not materialize the full array per shard
        return np.memmap(path, dtype=dtype, mode="r", shape=shape)

    return read
