"""Reshard geometry: map an N-way block-partitioned checkpoint onto an M-way
target sharding.

Reference analog: the fleet layer's elastic relaunch (elastic/manager.py)
plus the GroupSharded save/load pair — the reference persists each rank's
shard and rebuilds state dicts for whatever world size comes back. Here the
same idea is expressed as pure slice geometry over the saved **block index
map** (every array's global shape + the index each saved block covers):

* **identity** — target shard cuts equal the source block cuts: each target
  shard IS one saved block, passed through byte-identical (the N→N resume
  fast path; no slicing, no concatenation, no gather).
* **index-mapped** — the cut sets nest per dimension (every boundary of one
  is a boundary of the other, the N%M==0 / M%N==0 family, plus N→1 and
  1→M): each target shard is assembled from whole blocks and/or one
  contiguous sub-slice per block, reading only the bytes that land on this
  shard. Peak memory is one target shard, never the global array.
* **gather** — boundaries cross (3→2, or the sharded dim moved because the
  target world divides a different dimension): materialize the global array
  once from its blocks, then re-place. Correct everywhere, costs a
  full-array host buffer; :mod:`tools.metrics_summary` WARNs when a
  *nestable* world pair still lands here (an array's spec moved dims).

Pure numpy + slice math; jax enters only at :func:`place` (building the
target ``jax.Array`` via ``make_array_from_callback``).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["normalize_index", "target_indices", "classify", "ReshardPlan"]

Index = Tuple[Tuple[int, int], ...]  # ((start, stop), ...) per dim, concrete


def normalize_index(idx, shape) -> Index:
    """A tuple-of-slices (jax ``devices_indices_map`` style, Nones allowed)
    -> concrete ((start, stop), ...) covering exactly the same region."""
    out = []
    for i, dim in enumerate(shape):
        sl = idx[i] if idx is not None and i < len(idx) else slice(None)
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def target_indices(sharding, shape) -> List[Index]:
    """Distinct shard regions of ``sharding`` over ``shape`` (replicas
    deduplicated), sorted for determinism."""
    if sharding is None:
        return [normalize_index(None, shape)]
    seen = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        seen.setdefault(normalize_index(idx, shape), True)
    return sorted(seen)


def _cuts(indices: Sequence[Index], ndim: int) -> List[set]:
    """Per-dimension boundary sets of a block partition."""
    cuts = [set() for _ in range(ndim)]
    for idx in indices:
        for d, (a, b) in enumerate(idx):
            cuts[d].add(a)
            cuts[d].add(b)
    return cuts


def classify(src_indices: Sequence[Index], dst_indices: Sequence[Index],
             ndim: int) -> str:
    """'identity' | 'mapped' | 'gather' for this (source blocks, target
    shards) pair — see the module docstring for the semantics."""
    if set(src_indices) == set(dst_indices):
        return "identity"
    sc, dc = _cuts(src_indices, ndim), _cuts(dst_indices, ndim)
    for d in range(ndim):
        if not (sc[d] <= dc[d] or dc[d] <= sc[d]):
            return "gather"
    return "mapped"


def _intersect(a: Index, b: Index) -> Optional[Index]:
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def _local(region: Index, base: Index) -> Tuple[slice, ...]:
    """``region`` re-expressed in the coordinates of the ``base`` block."""
    return tuple(slice(a - b0, b - b0)
                 for (a, b), (b0, _b1) in zip(region, base))


def _nbytes(idx: Index, itemsize: int) -> int:
    return itemsize * int(math.prod(b - a for a, b in idx) or 1)


class ReshardPlan:
    """One array's read plan: saved blocks -> target shard regions.

    ``blocks`` maps each saved block's :data:`Index` to a zero-argument
    reader returning its numpy payload (readers are memoized here, so a
    block feeding several target shards loads once)."""

    def __init__(self, shape, dtype,
                 blocks: Dict[Index, Callable[[], np.ndarray]],
                 dst_indices: Sequence[Index]):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._blocks = dict(blocks)
        self._cache: Dict[Index, np.ndarray] = {}
        self.dst_indices = list(dst_indices)
        self.kind = classify(list(blocks), self.dst_indices, len(self.shape))
        self.bytes_read = 0
        self._full: Optional[np.ndarray] = None
        self._shards: Dict[Index, np.ndarray] = {}

    # ------------------------------------------------------------- plumbing

    def _read(self, idx: Index) -> np.ndarray:
        """The block's array — possibly a lazy memmap: bytes_read is
        accounted where regions are actually consumed (shard/_gathered),
        not here, so an index-mapped load is charged only for the slices
        it copies out."""
        arr = self._cache.get(idx)
        if arr is None:
            arr = self._blocks[idx]()
            self._cache[idx] = arr
        return arr

    def _gathered(self) -> np.ndarray:
        if self._full is None:
            full = np.empty(self.shape, self.dtype)
            for idx in self._blocks:
                full[tuple(slice(a, b) for a, b in idx)] = self._read(idx)
                self.bytes_read += _nbytes(idx, self.dtype.itemsize)
            self._full = full
        return self._full

    # ------------------------------------------------------------------ api

    def shard(self, dst: Index) -> np.ndarray:
        """The numpy payload for one target shard region."""
        out = self._shards.get(dst)
        if out is not None:
            return out
        if self.kind == "identity":
            # the saved block IS the shard: materialize it byte-exact
            out = np.asarray(self._read(dst))
            self.bytes_read += _nbytes(dst, self.dtype.itemsize)
        elif self.kind == "gather":
            out = self._gathered()[tuple(slice(a, b) for a, b in dst)]
        else:
            shape = tuple(b - a for a, b in dst)
            out = np.empty(shape, self.dtype)
            for bidx in self._blocks:
                inter = _intersect(bidx, dst)
                if inter is None:
                    continue
                out[_local(inter, dst)] = self._read(bidx)[_local(inter, bidx)]
                self.bytes_read += _nbytes(inter, self.dtype.itemsize)
        self._shards[dst] = out
        return out

    def place(self, sharding=None):
        """Materialize the target array: a ``jax.Array`` at ``sharding``
        (replicas served from the per-region cache — each distinct region is
        assembled once), or plain numpy when ``sharding`` is None."""
        if sharding is None:
            if self.kind == "identity" and len(self._blocks) == 1:
                return self.shard(next(iter(self._blocks)))
            return self._gathered()
        import jax

        def cb(raw_idx):
            return self.shard(normalize_index(raw_idx, self.shape))

        return jax.make_array_from_callback(self.shape, sharding, cb)
