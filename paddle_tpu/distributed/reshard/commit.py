"""Pod-wide commit: a multi-host snapshot is atomic fleet-wide.

Reference analog: the fleet checkpoint barrier — the elastic manager only
trusts a snapshot every worker finished, because a relaunch that resumes
from a half-written multi-host save silently loses ranks' state. Here the
launcher's HTTP KV master (launch/master.py — the store ElasticManager
already heartbeats through) doubles as the commit coordinator:

    rank 0                                 rank r > 0
    ------                                 ----------
    mkdir step_N.tmp
    write rank_0 payload
    PUT  .../<step>/token = <random>  -->  poll token (the tmp dir exists)
                                           write rank_r payload, fsync
                                      <--  PUT .../<step>/ack/<r> =
                                               {token, ts, files, bytes}
    poll acks (token match, ts fresh)
    rename tmp -> final
    build + write COMMIT manifest
    PUT .../<step>/commit = {token}   -->  poll commit -> done

A SIGKILL anywhere between a rank's payload landing and rank 0's COMMIT
write leaves the directory manifest-less — invisible to
``latest_checkpoint`` on EVERY rank, which is the whole point. Ack keys
carry a wall-clock stamp and a per-save random token: a crashed previous
incarnation re-saving the same step can never satisfy this save's barrier
(token mismatch), and acks older than ``ttl`` are ignored even on token
match (a wedged rank's ancient ack must not vouch for bytes that later
writes may have replaced).
"""
from __future__ import annotations

import json
import os
import secrets
import time
from typing import Any, Dict, Optional

__all__ = ["PodCommit", "PodCommitError", "from_env"]


class PodCommitError(RuntimeError):
    """The pod barrier failed (timeout / master unreachable); the save is
    NOT committed anywhere — the message names the missing ranks."""


class PodCommit:
    """One job's commit coordinator over the KV master."""

    def __init__(self, endpoint: str, job_id: str, rank: int, world: int,
                 timeout: float = 300.0, ttl: float = 900.0,
                 poll: float = 0.1, scope: str = ""):
        from ..launch.master import KVClient
        self._kv = KVClient(endpoint)
        self.endpoint = endpoint
        self.job_id = job_id
        self.rank = int(rank)
        self.world = int(world)
        self.timeout = timeout
        self.ttl = ttl
        self.poll = poll
        self.scope = scope
        # tokens this rank has already completed a save with, keyed
        # (scope, step): a RE-save of the same step must not accept the
        # previous save's still-published token as "rank 0 is ready" (see
        # wait_ready). SHARED across for_dir clones — the memory must
        # survive the per-save scoping copy.
        self._done_tokens: Dict[Any, str] = {}

    def for_dir(self, directory: str) -> "PodCommit":
        """A copy whose barrier keys are scoped to one snapshot directory:
        two jobs-phases saving to DIFFERENT directories at the same step
        must not satisfy each other's barriers. The completed-token memory
        is shared with the parent (clones are per-save)."""
        import hashlib
        scope = hashlib.sha256(
            os.path.abspath(directory).encode()).hexdigest()[:12]
        clone = PodCommit(self.endpoint, self.job_id, self.rank, self.world,
                          timeout=self.timeout, ttl=self.ttl, poll=self.poll,
                          scope=scope)
        clone._done_tokens = self._done_tokens
        return clone

    # ------------------------------------------------------------------ keys

    def _key(self, step: int, tail: str) -> str:
        scope = f"{self.scope}/" if self.scope else ""
        return f"/{self.job_id}/ckpt/{scope}{int(step)}/{tail}"

    def _wait(self, key: str, pred, what: str) -> str:
        deadline = time.time() + self.timeout
        while True:
            v = self._kv.get(key)
            if v is not None and pred(v):
                return v
            if time.time() > deadline:
                raise PodCommitError(
                    f"pod commit: rank {self.rank} timed out after "
                    f"{self.timeout:.0f}s waiting for {what} "
                    f"(key {key} on {self.endpoint})")
            time.sleep(self.poll)

    # ---------------------------------------------------------------- rank 0

    def publish_ready(self, step: int) -> str:
        """The tmp dir exists and rank 0's own payload is in it: open this
        save's barrier window under a fresh token.

        Stale keys from a PREVIOUS save of this step (a post-rollback
        re-save) are deleted first — most importantly the old ``commit``
        key, which a sibling rank could otherwise read together with the
        old token and return success without ever writing its payload."""
        for r in range(1, self.world):
            self._kv.delete(self._key(step, f"ack/{r}"))
        self._kv.delete(self._key(step, "commit"))
        token = secrets.token_hex(8)
        if not self._kv.put(self._key(step, "token"), token):
            raise PodCommitError(
                f"pod commit: cannot reach KV master {self.endpoint} "
                f"to open the step {step} barrier")
        return token

    def wait_acks(self, step: int, token: str) -> Dict[int, dict]:
        """Block until every non-zero rank acked this token (fresh)."""
        acks: Dict[int, dict] = {}
        deadline = time.time() + self.timeout
        while len(acks) < self.world - 1:
            for r in range(1, self.world):
                if r in acks:
                    continue
                v = self._kv.get(self._key(step, f"ack/{r}"))
                if v is None:
                    continue
                try:
                    a = json.loads(v)
                except ValueError:
                    continue
                if a.get("token") != token:
                    continue  # another incarnation's ack
                if abs(time.time() - float(a.get("ts", 0))) > self.ttl:
                    continue  # expired: do not trust these bytes
                acks[r] = a
            if len(acks) >= self.world - 1:
                break
            if time.time() > deadline:
                missing = sorted(set(range(1, self.world)) - set(acks))
                raise PodCommitError(
                    f"pod commit: step {step} barrier timed out after "
                    f"{self.timeout:.0f}s — no durable-payload ack from "
                    f"rank(s) {missing}; snapshot left uncommitted")
            time.sleep(self.poll)
        return acks

    def publish_commit(self, step: int, token: str, path: str):
        """Announce the on-disk COMMIT to the waiting ranks. The manifest is
        already durable when this runs, so a KV hiccup must not look like a
        failed save: retry briefly, then WARN and return — the sibling
        ranks' wait_commit timeout is the honest signal of the coordination
        (not data) failure, and the snapshot stays fully resumable."""
        body = json.dumps({"token": token, "ts": time.time(), "path": path})
        deadline = time.time() + min(self.timeout, 30.0)
        while not self._kv.put(self._key(step, "commit"), body):
            if time.time() > deadline:
                import warnings
                warnings.warn(
                    f"pod commit: step {step} IS committed on disk but the "
                    f"KV master {self.endpoint} could not be told — sibling "
                    f"ranks will time out waiting for the commit key",
                    RuntimeWarning)
                return
            time.sleep(self.poll)

    # -------------------------------------------------------------- rank > 0

    def wait_ready(self, step: int) -> str:
        """Block until rank 0 opened the barrier; returns the save token.

        A token this rank already COMPLETED a save of this step with is the
        previous barrier's leftover, not rank 0 being ready — keep polling
        until rank 0 publishes a fresh one (publish_ready also deletes the
        stale commit key, so the old token cannot reach a false success)."""
        done = self._done_tokens.get((self.scope, int(step)))
        return self._wait(self._key(step, "token"),
                          lambda v: bool(v) and v != done,
                          "rank 0 to open the save window")

    def ack(self, step: int, token: str, info: Optional[Dict[str, Any]] = None):
        """My payload is durable (written + fsynced) under the tmp dir."""
        body = {"token": token, "ts": time.time(), "rank": self.rank}
        body.update(info or {})
        if not self._kv.put(self._key(step, f"ack/{self.rank}"),
                            json.dumps(body)):
            raise PodCommitError(
                f"pod commit: rank {self.rank} cannot reach KV master "
                f"{self.endpoint} to ack step {step}")

    def wait_commit(self, step: int, token: str) -> dict:
        v = self._wait(self._key(step, "commit"),
                       lambda v: _token_of(v) == token,
                       "rank 0's pod-wide COMMIT")
        # supersession guard: if rank 0 has already opened a NEWER barrier
        # for this step, the commit we just matched is history — our
        # payload is not part of whatever is durable now
        current = self._kv.get(self._key(step, "token"))
        if current is not None and current != token:
            raise PodCommitError(
                f"pod commit: step {step} was superseded by a newer save "
                f"while rank {self.rank} waited for the COMMIT")
        self._done_tokens[(self.scope, int(step))] = token
        return json.loads(v)


def _token_of(v: str):
    try:
        return json.loads(v).get("token")
    except ValueError:
        return None


def from_env(timeout: Optional[float] = None) -> Optional[PodCommit]:
    """Build the coordinator from the launcher env contract, or None for
    single-process jobs. ``PADDLE_CKPT_MASTER`` (the KV master endpoint) is
    exported by the launch controller when a rendezvous master exists."""
    endpoint = os.environ.get("PADDLE_CKPT_MASTER")
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    except ValueError:
        world = 1
    if not endpoint or world <= 1:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    job = os.environ.get("PADDLE_JOB_ID", "default")
    kw = {} if timeout is None else {"timeout": timeout}
    return PodCommit(endpoint, job, rank, world, **kw)
