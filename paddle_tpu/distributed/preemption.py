"""Preemption watcher: turn SIGTERM/SIGINT into a checkpoint request.

Reference analog: fluid/incubate/checkpoint/auto_checkpoint.py's periodic
job snapshots assume something outside the train loop decides "save NOW and
exit"; on preemptible TPU slices that something is the eviction SIGTERM the
node agent delivers with a short grace window.

The watcher never acts inside the (async-signal) handler — it only records
the request. The training loop observes ``requested()`` at its next step
boundary and performs the emergency checkpoint there, where the model,
optimizer and scaler are in a consistent between-steps state. hapi wires
this through ``callbacks.AutoCheckpoint``; raw ``jit.TrainStep`` loops poll
the watcher directly::

    with PreemptionWatcher() as w:
        for step, batch in enumerate(loader):
            train_step(*batch)
            if w.requested():
                train_step.save_checkpoint(ckpt_dir, step, block=True)
                break

Serving uses the same watcher for graceful drain:
``DecodeEngine.drain_on_preemption(grace_s=...)`` installs (or adopts) it,
and the engine's next step boundary after SIGTERM begins a drain — the
door answers ``rejected_draining``, live requests finish or expire within
the grace budget, and the process exits clean instead of dying mid-token
(tests/test_serve_drain_e2e.py).

Signal handlers install on the MAIN thread only (CPython restriction);
elsewhere ``install()`` degrades to a no-op watcher that never fires, so
library code can install unconditionally.
"""
from __future__ import annotations

import signal
import threading
import time
from typing import Callable, Optional, Sequence

from .. import monitor as _monitor

__all__ = ["PreemptionWatcher", "install", "requested", "clear", "get"]

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionWatcher:
    """Records the first delivery of any watched signal.

    A second SIGINT escalates to the previous handler (normally
    ``KeyboardInterrupt``) so a user hammering Ctrl-C still gets an abort
    even if the emergency checkpoint hangs; a second SIGTERM stays recorded
    only (the launcher's grace-then-kill already bounds shutdown time).
    """

    def __init__(self, signals: Sequence[int] = _DEFAULT_SIGNALS,
                 on_signal: Optional[Callable[[int], None]] = None):
        self._signals = tuple(signals)
        self._on_signal = on_signal
        self._event = threading.Event()
        self._prev = {}
        self._reported = False
        self.installed = False
        self.signum: Optional[int] = None
        self.when: Optional[float] = None

    # ------------------------------------------------------------- lifecycle

    def install(self) -> "PreemptionWatcher":
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal would raise; stay a never-firing stub
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handle)
        except ValueError:
            # embedded interpreter corner cases: degrade, don't break training
            for s, h in self._prev.items():
                signal.signal(s, h)
            self._prev.clear()
            return self
        self.installed = True
        return self

    def uninstall(self):
        if not self.installed:
            return
        for s, h in self._prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionWatcher":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # --------------------------------------------------------------- handler

    def _handle(self, signum, frame):
        first = not self._event.is_set()
        if first:
            # record ONLY — no locks here. The handler interrupts the main
            # thread at an arbitrary bytecode; touching the monitor's
            # non-reentrant registry/sink locks from here can self-deadlock
            # against a metric op the interrupted frame holds mid-update.
            # The telemetry event is emitted from requested() instead.
            self.signum = signum
            self.when = time.time()
            self._event.set()
            if self._on_signal is not None:
                # user hook: runs in async-signal context — keep it trivial
                try:
                    self._on_signal(signum)
                except Exception:
                    pass
            return
        if signum == signal.SIGINT:
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                raise KeyboardInterrupt

    # ----------------------------------------------------------------- query

    def requested(self) -> bool:
        """True once a watched signal arrived; the step boundary that sees
        this should emergency-checkpoint and wind down."""
        if not self._event.is_set():
            return False
        if not self._reported:
            # deferred from the handler: we are on a normal call stack now,
            # so the monitor's locks are safe to take
            self._reported = True
            mon = _monitor._active
            if mon is not None:
                try:
                    mon.preempted(self.signum or 0)
                except Exception:
                    pass
        return True

    def clear(self):
        self._event.clear()
        self._reported = False
        self.signum = None
        self.when = None


# --------------------------------------------------------- module-level sugar

_global: Optional[PreemptionWatcher] = None


def install(signals: Sequence[int] = _DEFAULT_SIGNALS) -> PreemptionWatcher:
    """Install (or return) the process-wide watcher."""
    global _global
    if _global is None:
        _global = PreemptionWatcher(signals)
    _global.install()
    return _global


def requested() -> bool:
    return _global is not None and _global.requested()


def clear():
    if _global is not None:
        _global.clear()


def get() -> Optional[PreemptionWatcher]:
    """The process-wide watcher, or None if install() was never called —
    lets tooling observe preemption state without installing handlers as
    a side effect. (install() itself is idempotent and returns the same
    watcher, which is how DecodeEngine.drain_on_preemption shares it with
    a training loop's AutoCheckpoint.)"""
    return _global
