"""Hybrid-parallel optimizers.

Reference analogs:
- HybridParallelOptimizer (fleet/meta_optimizers/dygraph_optimizer/
  hybrid_parallel_optimizer.py): wraps the inner optimizer, fixes grad clipping to
  allreduce the global norm across model/pipe groups before clipping.
- DygraphShardingOptimizer (dygraph_sharding_optimizer.py): ZeRO stage 1 — each rank
  owns a param shard's optimizer state; step updates owned shards then allgathers.

TPU-native: gradients and parameters are global arrays, so the global-norm clip is
already global — no cross-group fix-up needed. ZeRO stage 1/2 = placing the optimizer
state (and grads) sharded over the "sharding" axis: the update math is unchanged, XLA
partitions the fused update, and the "allgather after step" is the (free) resharding
of the updated parameter back to its replicated placement.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..env import get_mesh


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)

    @property
    def inner_opt(self):
        return self._inner_opt


def _shard_spec_for(shape, axis_size, existing=None):
    """Shard the largest dim divisible by the sharding degree.

    `existing` (a PartitionSpec from the param's current placement, e.g. TP's
    P(None, "model")) is preserved: the "sharding" axis lands on the largest
    divisible dim that is still free, so ZeRO composes with tensor parallelism
    instead of clobbering it (reference GroupShardedStage3 + mp hybrid)."""
    spec = [None] * len(shape)
    if existing is not None:
        for i, s in enumerate(tuple(existing)[:len(shape)]):
            spec[i] = s
        used = {a for s in spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))}
        if "sharding" in used:
            return P(*spec)  # already sharded (idempotent re-application)
    best, best_size = -1, 0
    for i, d in enumerate(shape):
        if spec[i] is not None:
            continue
        if d % axis_size == 0 and d >= axis_size and d > best_size:
            best, best_size = i, d
    if best >= 0:
        spec[best] = "sharding"
    return P(*spec)


def _existing_spec(arr):
    """PartitionSpec of an array's current NamedSharding placement, if any."""
    sh = getattr(arr, "sharding", None)
    return getattr(sh, "spec", None)


_HOST_MEMORY_OK: Optional[bool] = None
_HOST_WARNED = False


def _host_memory_supported() -> bool:
    """Probe once whether this backend supports pinned_host placements."""
    global _HOST_MEMORY_OK
    if _HOST_MEMORY_OK is None:
        import jax.numpy as jnp
        try:
            dev = jax.devices()[0]
            sharding = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
            jax.device_put(jnp.zeros((1,)), sharding)
            _HOST_MEMORY_OK = True
        except Exception:
            _HOST_MEMORY_OK = False
    return _HOST_MEMORY_OK


def _maybe_host(sharding, offload):
    """Move a sharding to host memory for ZeRO offload where supported."""
    if not offload:
        return sharding
    if not _host_memory_supported():
        # warn ONCE per process: the placement hook routes every state
        # creation through here (one call per param per state buffer)
        global _HOST_WARNED
        if not _HOST_WARNED:
            _HOST_WARNED = True
            import warnings
            warnings.warn("offload=True but this backend has no host memory "
                          "kinds; optimizer states stay on device",
                          stacklevel=3)
        return sharding
    return sharding.with_memory_kind("pinned_host")


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1: optimizer states sharded over the "sharding" mesh axis.

    The reference's shard-ownership bookkeeping (param→rank maps, allgather after
    step) collapses to a placement rule on the state pytree; the compiled fused
    update reads sharded states + replicated grads and emits exactly the
    reduce-scatter/all-gather traffic ZeRO describes.
    """

    def __init__(self, optimizer, hcg=None, strategy=None, offload=False,
                 grad_bucket_bytes=None):
        super().__init__(optimizer, hcg, strategy)
        self._sharding_placed = set()
        self._offload = offload
        # collective-coalescing knob consumed by jit.TrainStep: per-microbatch
        # reduce-scatters of grads smaller than this are fused into flat
        # buckets (None = adapter default, 0 = per-param collectives)
        self._grad_bucket_bytes = grad_bucket_bytes
        # param placement BEFORE the update, so the eager step can restore it
        # after (the ZeRO "all-gather after step": the jitted fused update
        # propagates the states' shard layout onto the new params)
        self._param_placements = {}
        # install the placement hook NOW, not in _place_states: both step()
        # and TrainStep.__init__ run _ensure_all_states() before placement,
        # and a hook installed after that point never sees a state creation —
        # every buffer would materialize full-size replicated first, the
        # transient allocation ZeRO exists to avoid. The hook checks the mesh
        # at call time, so pre-mesh installation is safe (returns None).
        # Install on the RAW Optimizer (the one whose _ensure_state reads
        # it): an intermediate wrapper (e.g. GradientMergeOptimizer) only
        # delegates attribute READS, so setting on it would strand the hook.
        raw = self._inner_opt
        while hasattr(raw, "_inner_opt"):
            raw = raw._inner_opt
        raw._state_placement_fn = self._state_sharding

    def _state_sharding(self, p, name, shape):
        """Shard placement for one optimizer-state (or master-weight) buffer.

        Installed as the inner optimizer's ``_state_placement_fn`` so lazily
        created states are born shard-sized; also used by ``_place_states``
        to migrate states that predate the wrapper."""
        mesh = get_mesh()
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return None
        existing = _existing_spec(p.value()) if len(shape) == p.ndim else None
        spec = _shard_spec_for(shape, mesh.shape["sharding"], existing)
        return _maybe_host(NamedSharding(mesh, spec), self._offload)

    def _place_states(self):
        mesh = get_mesh()
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return
        opt = self._inner_opt
        for p in opt._parameter_list:
            pid = id(p)
            self._param_placements.setdefault(
                pid, getattr(p.value(), "sharding", None))
            if pid in self._sharding_placed or pid not in opt._accumulators:
                continue
            states = opt._accumulators[pid]
            for name, arr in states.items():
                sh = self._state_sharding(p, name, arr.shape)
                if sh is not None and getattr(arr, "sharding", None) != sh:
                    states[name] = jax.device_put(arr, sh)
            if pid in opt._master_weights:
                mw = opt._master_weights[pid]
                sh = self._state_sharding(p, "master", mw.shape)
                if sh is not None and getattr(mw, "sharding", None) != sh:
                    opt._master_weights[pid] = jax.device_put(mw, sh)
            self._sharding_placed.add(pid)

    def _restore_param_placements(self):
        """ZeRO's update-then-all-gather for the EAGER step path: the fused
        update reads shard-placed states, so XLA's propagation hands back
        shard-placed new params; gather them back to their mesh placement
        (compiled TrainStep does this inside the executable instead).

        Params that carried a mesh placement (TP spec, stage-3 shard,
        explicit replication) go back to exactly that; params that predate
        the mesh (single-device) are all-gathered to mesh-replicated — they
        must NOT go back to one device, which would be device-incompatible
        with the mesh-committed optimizer states on the next step."""
        mesh = get_mesh()
        # same guard as _place_states: on a mesh without a populated
        # "sharding" axis nothing was sharded, and force-replicating here
        # would un-shard TP params and all-gather the model every step
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return
        for p in self._inner_opt._parameter_list:
            want = self._param_placements.get(id(p))
            if not isinstance(want, NamedSharding):
                want = NamedSharding(mesh, P())
            have = getattr(p._data, "sharding", None)
            if have is not None and have != want:
                from ...core.lazy import lazy_device_put
                p._data = lazy_device_put(p.value(), want)

    def _shard_state_bytes(self) -> int:
        """Per-device bytes held by optimizer states + master weights (the
        ``shard/opt_state_bytes`` gauge): shard-sized buffers count 1/world,
        replicated ones full size."""
        import math
        opt = self._inner_opt
        total = 0
        arrays = [a for st in opt._accumulators.values() for a in st.values()]
        arrays += list(opt._master_weights.values())
        for a in arrays:
            try:
                shard_shape = a.sharding.shard_shape(a.shape)
                total += a.dtype.itemsize * int(
                    math.prod(shard_shape) if shard_shape else 1)
            except Exception:
                total += int(getattr(a, "nbytes", 0))
        return total

    def _move_states(self, to_host: bool):
        """Offload paging: states live on host between steps, on device during
        the update (reference GroupShardedStage3 cpu_offload semantics)."""
        opt = self._inner_opt
        if not _host_memory_supported():
            return  # _maybe_host already warned; nothing is paged

        def move(arr):
            sh = getattr(arr, "sharding", None)
            if sh is None:
                return arr
            kind = "pinned_host" if to_host else "device"
            return jax.device_put(arr, sh.with_memory_kind(kind))
        for pid, states in opt._accumulators.items():
            for name in states:
                states[name] = move(states[name])
        for pid in list(opt._master_weights):
            opt._master_weights[pid] = move(opt._master_weights[pid])

    def step(self):
        # states are created lazily on first step; place them before the fused update
        self._inner_opt._ensure_all_states()
        self._place_states()
        if not self._offload:
            out = self._inner_opt.step()
            self._restore_param_placements()
            return out
        self._move_states(to_host=False)
        try:
            out = self._inner_opt.step()
        finally:
            self._move_states(to_host=True)
        self._restore_param_placements()
        return out
