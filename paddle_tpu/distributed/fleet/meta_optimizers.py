"""Hybrid-parallel optimizers.

Reference analogs:
- HybridParallelOptimizer (fleet/meta_optimizers/dygraph_optimizer/
  hybrid_parallel_optimizer.py): wraps the inner optimizer, fixes grad clipping to
  allreduce the global norm across model/pipe groups before clipping.
- DygraphShardingOptimizer (dygraph_sharding_optimizer.py): ZeRO stage 1 — each rank
  owns a param shard's optimizer state; step updates owned shards then allgathers.

TPU-native: gradients and parameters are global arrays, so the global-norm clip is
already global — no cross-group fix-up needed. ZeRO stage 1/2 = placing the optimizer
state (and grads) sharded over the "sharding" axis: the update math is unchanged, XLA
partitions the fused update, and the "allgather after step" is the (free) resharding
of the updated parameter back to its replicated placement.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..env import get_mesh


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)

    @property
    def inner_opt(self):
        return self._inner_opt


def _shard_spec_for(shape, axis_size):
    """First dim divisible by the sharding degree → shard it, else replicate."""
    if len(shape) >= 1 and shape[0] % axis_size == 0 and shape[0] >= axis_size:
        return P("sharding", *([None] * (len(shape) - 1)))
    return P(*([None] * len(shape)))


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1: optimizer states sharded over the "sharding" mesh axis.

    The reference's shard-ownership bookkeeping (param→rank maps, allgather after
    step) collapses to a placement rule on the state pytree; the compiled fused
    update reads sharded states + replicated grads and emits exactly the
    reduce-scatter/all-gather traffic ZeRO describes.
    """

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        self._sharding_placed = set()

    def _place_states(self):
        mesh = get_mesh()
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return
        opt = self._inner_opt
        for p in opt._parameter_list:
            pid = id(p)
            if pid in self._sharding_placed or pid not in opt._accumulators:
                continue
            states = opt._accumulators[pid]
            for name, arr in states.items():
                spec = _shard_spec_for(arr.shape, mesh.shape["sharding"])
                states[name] = jax.device_put(arr, NamedSharding(mesh, spec))
            if pid in opt._master_weights:
                mw = opt._master_weights[pid]
                spec = _shard_spec_for(mw.shape, mesh.shape["sharding"])
                opt._master_weights[pid] = jax.device_put(
                    mw, NamedSharding(mesh, spec))
            self._sharding_placed.add(pid)

    def step(self):
        # states are created lazily on first step; place them before the fused update
        self._inner_opt._ensure_all_states()
        self._place_states()
        return self._inner_opt.step()
