"""Hybrid-parallel optimizers.

Reference analogs:
- HybridParallelOptimizer (fleet/meta_optimizers/dygraph_optimizer/
  hybrid_parallel_optimizer.py): wraps the inner optimizer, fixes grad clipping to
  allreduce the global norm across model/pipe groups before clipping.
- DygraphShardingOptimizer (dygraph_sharding_optimizer.py): ZeRO stage 1 — each rank
  owns a param shard's optimizer state; step updates owned shards then allgathers.

TPU-native: gradients and parameters are global arrays, so the global-norm clip is
already global — no cross-group fix-up needed. ZeRO stage 1/2 = placing the optimizer
state (and grads) sharded over the "sharding" axis: the update math is unchanged, XLA
partitions the fused update, and the "allgather after step" is the (free) resharding
of the updated parameter back to its replicated placement.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..env import get_mesh


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)

    @property
    def inner_opt(self):
        return self._inner_opt


def _shard_spec_for(shape, axis_size, existing=None):
    """Shard the largest dim divisible by the sharding degree.

    `existing` (a PartitionSpec from the param's current placement, e.g. TP's
    P(None, "model")) is preserved: the "sharding" axis lands on the largest
    divisible dim that is still free, so ZeRO composes with tensor parallelism
    instead of clobbering it (reference GroupShardedStage3 + mp hybrid)."""
    spec = [None] * len(shape)
    if existing is not None:
        for i, s in enumerate(tuple(existing)[:len(shape)]):
            spec[i] = s
        used = {a for s in spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))}
        if "sharding" in used:
            return P(*spec)  # already sharded (idempotent re-application)
    best, best_size = -1, 0
    for i, d in enumerate(shape):
        if spec[i] is not None:
            continue
        if d % axis_size == 0 and d >= axis_size and d > best_size:
            best, best_size = i, d
    if best >= 0:
        spec[best] = "sharding"
    return P(*spec)


def _existing_spec(arr):
    """PartitionSpec of an array's current NamedSharding placement, if any."""
    sh = getattr(arr, "sharding", None)
    return getattr(sh, "spec", None)


_HOST_MEMORY_OK: Optional[bool] = None


def _host_memory_supported() -> bool:
    """Probe once whether this backend supports pinned_host placements."""
    global _HOST_MEMORY_OK
    if _HOST_MEMORY_OK is None:
        import jax.numpy as jnp
        try:
            dev = jax.devices()[0]
            sharding = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
            jax.device_put(jnp.zeros((1,)), sharding)
            _HOST_MEMORY_OK = True
        except Exception:
            _HOST_MEMORY_OK = False
    return _HOST_MEMORY_OK


def _maybe_host(sharding, offload):
    """Move a sharding to host memory for ZeRO offload where supported."""
    if not offload:
        return sharding
    if not _host_memory_supported():
        import warnings
        warnings.warn("offload=True but this backend has no host memory kinds;"
                      " optimizer states stay on device", stacklevel=3)
        return sharding
    return sharding.with_memory_kind("pinned_host")


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1: optimizer states sharded over the "sharding" mesh axis.

    The reference's shard-ownership bookkeeping (param→rank maps, allgather after
    step) collapses to a placement rule on the state pytree; the compiled fused
    update reads sharded states + replicated grads and emits exactly the
    reduce-scatter/all-gather traffic ZeRO describes.
    """

    def __init__(self, optimizer, hcg=None, strategy=None, offload=False):
        super().__init__(optimizer, hcg, strategy)
        self._sharding_placed = set()
        self._offload = offload

    def _place_states(self):
        mesh = get_mesh()
        if mesh is None or mesh.shape.get("sharding", 1) <= 1:
            return
        opt = self._inner_opt
        for p in opt._parameter_list:
            pid = id(p)
            if pid in self._sharding_placed or pid not in opt._accumulators:
                continue
            existing = _existing_spec(p.value())
            states = opt._accumulators[pid]
            for name, arr in states.items():
                spec = _shard_spec_for(arr.shape, mesh.shape["sharding"],
                                       existing if arr.ndim == p.ndim else None)
                sh = _maybe_host(NamedSharding(mesh, spec), self._offload)
                states[name] = jax.device_put(arr, sh)
            if pid in opt._master_weights:
                mw = opt._master_weights[pid]
                spec = _shard_spec_for(mw.shape, mesh.shape["sharding"],
                                       existing)
                sh = _maybe_host(NamedSharding(mesh, spec), self._offload)
                opt._master_weights[pid] = jax.device_put(mw, sh)
            self._sharding_placed.add(pid)

    def _move_states(self, to_host: bool):
        """Offload paging: states live on host between steps, on device during
        the update (reference GroupShardedStage3 cpu_offload semantics)."""
        opt = self._inner_opt
        if not _host_memory_supported():
            return  # _maybe_host already warned; nothing is paged

        def move(arr):
            sh = getattr(arr, "sharding", None)
            if sh is None:
                return arr
            kind = "pinned_host" if to_host else "device"
            return jax.device_put(arr, sh.with_memory_kind(kind))
        for pid, states in opt._accumulators.items():
            for name in states:
                states[name] = move(states[name])
        for pid in list(opt._master_weights):
            opt._master_weights[pid] = move(opt._master_weights[pid])

    def step(self):
        # states are created lazily on first step; place them before the fused update
        self._inner_opt._ensure_all_states()
        self._place_states()
        if not self._offload:
            return self._inner_opt.step()
        self._move_states(to_host=False)
        try:
            return self._inner_opt.step()
        finally:
            self._move_states(to_host=True)
