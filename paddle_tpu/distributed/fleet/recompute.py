"""Activation recompute (gradient checkpointing).

Reference analog: fleet/recompute/recompute.py — a PyLayer that runs forward under
no_grad saving only inputs + RNG state, then re-runs it with grad during backward
(RNG replayed so dropout masks match).

Same structure here on the tape: forward under no_grad, a custom GradNode whose
backward re-executes the function eagerly (RNG state restored) and backpropagates
through the recomputed subgraph via autograd.grad — parameter grads accumulate as a
side effect exactly like the reference's inner backward. Under a to_static trace,
jax.checkpoint is the whole story and we simply mark the region.
"""
from __future__ import annotations

from typing import Any

from ...core import dispatch
from ...core import random as rnd
from ...core.autograd import GradNode, run_backward
from ...core.tensor import Tensor


def _flatten_tensors(obj, out):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _flatten_tensors(o, out)
    elif isinstance(obj, dict):
        for o in obj.values():
            _flatten_tensors(o, out)


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs) -> Any:
    """paddle.distributed.fleet.utils.recompute parity."""
    if dispatch.in_trace() or not dispatch.is_grad_enabled():
        # traced: XLA remat handles it; no-grad: nothing to save anyway
        return function(*args, **kwargs)

    in_tensors: list = []
    _flatten_tensors((args, kwargs), in_tensors)
    diff_inputs = [t for t in in_tensors if not t.stop_gradient]

    rng_before = rnd.get_rng_state() if preserve_rng_state else None

    with dispatch.no_grad():
        outs = function(*args, **kwargs)

    single = isinstance(outs, Tensor)
    out_list = [outs] if single else [o for o in outs if isinstance(o, Tensor)]
    if not diff_inputs:
        return outs

    def _detach(obj, mapping):
        # sever the recomputed subgraph at the inputs: leaves here, so the inner
        # backward cannot walk (and release) the OUTER graph's nodes
        if isinstance(obj, Tensor):
            if id(obj) not in mapping:
                mapping[id(obj)] = Tensor(obj.value(),
                                          stop_gradient=obj.stop_gradient)
            return mapping[id(obj)]
        if isinstance(obj, (list, tuple)):
            mapped = [_detach(o, mapping) for o in obj]
            return type(obj)(mapped) if isinstance(obj, tuple) else mapped
        if isinstance(obj, dict):
            return {k: _detach(v, mapping) for k, v in obj.items()}
        return obj

    def bwd(primals, saved_outs, cotangents):
        rng_save = None
        if rng_before is not None:
            rng_save = rnd.get_rng_state()
            rnd.set_rng_state(rng_before)
        try:
            mapping = {}
            dargs = _detach(list(args), mapping)
            dkwargs = _detach(kwargs, mapping)
            detached_diff = [mapping[id(t)] for t in diff_inputs]
            with dispatch.enable_grad():
                re_out = function(*dargs, **dkwargs)
            re_list = [re_out] if isinstance(re_out, Tensor) else \
                [o for o in re_out if isinstance(o, Tensor)]
            cots = [Tensor(c) for c in cotangents[:len(re_list)]]
            # run_backward (not grad()): parameter grads must accumulate as a
            # side effect, like the reference's inner backward — grad() is
            # deliberately side-effect-free on non-input leaves
            for d in detached_diff:
                d._retain_grad_flag = True
            run_backward(re_list, cots)
            return [d._grad for d in detached_diff]
        finally:
            if rng_save is not None:
                rnd.set_rng_state(rng_save)

    node = GradNode(
        name="recompute", bwd_fn=bwd, mode="explicit",
        saved_primals=None, saved_outs=None,
        diff_idx=tuple(range(len(diff_inputs))),
        input_tensors=tuple(diff_inputs),
        out_metas=tuple((tuple(o.shape), o.dtype) for o in out_list))

    for i, o in enumerate(out_list):
        o.stop_gradient = False
        o._grad_node = node
        o._out_index = i
    return outs
