"""Activation recompute (gradient checkpointing).

Reference analog: fleet/recompute/recompute.py — a PyLayer that runs forward under
no_grad saving only inputs + RNG state, then re-runs it with grad during backward
(RNG replayed so dropout masks match).

Same structure here on the tape: forward under no_grad, a custom GradNode whose
backward re-executes the function eagerly (RNG state restored) and backpropagates
through the recomputed subgraph via autograd.grad — parameter grads accumulate as a
side effect exactly like the reference's inner backward. Under a to_static trace,
jax.checkpoint is the whole story and we simply mark the region.

Rematerialization POLICIES (``policy=`` kwarg, compiled path):

* ``"full"`` (default) — plain ``jax.checkpoint``: only the region inputs
  survive; everything recomputes in backward. Maximum memory back, ~33%
  extra FLOPs (a second forward).
* ``"dots"`` — ``dots_with_no_batch_dims_saveable``: matmul outputs stay,
  elementwise chains recompute. Cheap recompute, moderate memory.
* ``"selective"`` — ``save_only_these_names`` over the canonical activation
  names (``core.remat.SELECTIVE_SAVE_NAMES``: qkv projection, attention
  context, attention output, first MLP matmul). The UNNAMED attention
  score/softmax region — every [B, H, S, S] tensor — is dropped and
  recomputed: Megatron-style selective recomputation, most of full
  checkpointing's memory for a few percent recompute FLOPs.
* any ``jax.checkpoint_policies`` callable passes through.

The eager tape path accepts ``policy`` for API uniformity but always
recomputes the whole region (the PyLayer form saves only inputs + RNG state
by construction — there is no residual store to be selective about).
"""
from __future__ import annotations

from typing import Any

from ...core import dispatch
from ...core import random as rnd
from ...core import remat as _remat
from ...core.autograd import GradNode, run_backward
from ...core.tensor import Tensor


def _flatten_tensors(obj, out):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _flatten_tensors(o, out)
    elif isinstance(obj, dict):
        for o in obj.values():
            _flatten_tensors(o, out)


def _recompute_traced(function, args, kwargs, policy=None):
    """jax.checkpoint over the region inside an active trace.

    The function's INPUT tensors become the checkpoint arguments (their
    residuals are what remat drops); parameters captured by closure are traced
    as usual and recomputation re-reads them.

    Stateful side effects inside the region (dropout RNG chain advances, BN
    running-stat writes) are captured in a NESTED TraceContext and threaded
    OUT of the checkpoint as extra outputs — otherwise a remat-scope tracer
    would escape into the outer trace's buffer state (UnexpectedTracerError)."""
    import jax

    in_tensors: list = []
    _flatten_tensors((args, kwargs), in_tensors)
    arrays = tuple(t.value() for t in in_tensors)

    out_struct = {}

    def pure(arrs):
        saved = [t._data for t in in_tensors]
        inner_ctx = dispatch.TraceContext()
        dispatch.push_trace(inner_ctx)
        for t, a in zip(in_tensors, arrs):
            t._data = a
        try:
            out = function(*args, **kwargs)
            outs: list = []
            _flatten_tensors(out, outs)
            out_struct["single"] = isinstance(out, Tensor)
            out_struct["template"] = out
            out_struct["n_out"] = len(outs)
            out_struct["side_tensors"] = [t for t, _ in
                                          inner_ctx.buffer_updates]
            side_arrays = tuple(a for _, a in inner_ctx.buffer_updates)
            return tuple(o.value() for o in outs) + side_arrays
        finally:
            dispatch.pop_trace()
            inner_ctx.restore()
            for t, d in zip(in_tensors, saved):
                t._data = d

    jax_policy = _remat.resolve_policy(policy)
    _remat.note_region(policy if isinstance(policy, str) else jax_policy)
    out_arrays = jax.checkpoint(pure, policy=jax_policy)(arrays)
    n_out = out_struct["n_out"]
    # re-emit the region's buffer updates into the OUTER trace so TrainStep /
    # to_static thread them as program state (post-checkpoint values)
    outer_ctx = dispatch.trace_ctx()
    for t, arr in zip(out_struct["side_tensors"], out_arrays[n_out:]):
        t._data = arr
        if outer_ctx is not None:
            outer_ctx.record_buffer_update(t, arr)
    if out_struct["single"]:
        return Tensor(out_arrays[0])
    # rebuild: replace each Tensor leaf of the template in order
    it = iter(out_arrays[:n_out])

    def rebuild(obj):
        if isinstance(obj, Tensor):
            return Tensor(next(it))
        if isinstance(obj, (list, tuple)):
            built = [rebuild(o) for o in obj]
            return type(obj)(built) if isinstance(obj, tuple) else built
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        return obj

    return rebuild(out_struct["template"])


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, policy="full", **kwargs) -> Any:
    """paddle.distributed.fleet.utils.recompute parity, plus ``policy=``
    (see module docstring: "full" | "dots" | "selective" | jax policy)."""
    _remat.resolve_policy(policy)  # validate up front, both paths
    if dispatch.in_trace():
        # under jit/TrainStep tracing, apply jax.checkpoint so the compiled
        # program actually drops this region's residuals and recomputes them
        # in backward (a pass-through here would silently lose the memory
        # saving the user asked for). Health activation taps are suspended
        # for the region: a value recorded inside jax.checkpoint is an
        # inner-trace tracer that cannot escape to the step's outputs.
        from ...monitor.health import suspend_taps
        with suspend_taps():
            return _recompute_traced(function, args, kwargs, policy)
    if not dispatch.is_grad_enabled():
        return function(*args, **kwargs)  # nothing to save anyway

    in_tensors: list = []
    _flatten_tensors((args, kwargs), in_tensors)
    diff_inputs = [t for t in in_tensors if not t.stop_gradient]

    rng_before = rnd.get_rng_state() if preserve_rng_state else None

    with dispatch.no_grad():
        outs = function(*args, **kwargs)

    single = isinstance(outs, Tensor)
    out_list = [outs] if single else [o for o in outs if isinstance(o, Tensor)]
    if not diff_inputs:
        return outs

    def _detach(obj, mapping):
        # sever the recomputed subgraph at the inputs: leaves here, so the inner
        # backward cannot walk (and release) the OUTER graph's nodes
        if isinstance(obj, Tensor):
            if id(obj) not in mapping:
                mapping[id(obj)] = Tensor(obj.value(),
                                          stop_gradient=obj.stop_gradient)
            return mapping[id(obj)]
        if isinstance(obj, (list, tuple)):
            mapped = [_detach(o, mapping) for o in obj]
            return type(obj)(mapped) if isinstance(obj, tuple) else mapped
        if isinstance(obj, dict):
            return {k: _detach(v, mapping) for k, v in obj.items()}
        return obj

    def bwd(primals, saved_outs, cotangents):
        rng_save = None
        if rng_before is not None:
            rng_save = rnd.get_rng_state()
            rnd.set_rng_state(rng_before)
        try:
            mapping = {}
            dargs = _detach(list(args), mapping)
            dkwargs = _detach(kwargs, mapping)
            detached_diff = [mapping[id(t)] for t in diff_inputs]
            with dispatch.enable_grad():
                re_out = function(*dargs, **dkwargs)
            re_list = [re_out] if isinstance(re_out, Tensor) else \
                [o for o in re_out if isinstance(o, Tensor)]
            cots = [Tensor(c) for c in cotangents[:len(re_list)]]
            # run_backward (not grad()): parameter grads must accumulate as a
            # side effect, like the reference's inner backward — grad() is
            # deliberately side-effect-free on non-input leaves
            for d in detached_diff:
                d._retain_grad_flag = True
            run_backward(re_list, cots)
            return [d._grad for d in detached_diff]
        finally:
            if rng_save is not None:
                rnd.set_rng_state(rng_save)

    node = GradNode(
        name="recompute", bwd_fn=bwd, mode="explicit",
        saved_primals=None, saved_outs=None,
        diff_idx=tuple(range(len(diff_inputs))),
        input_tensors=tuple(diff_inputs),
        out_metas=tuple((tuple(o.shape), o.dtype) for o in out_list))

    for i, o in enumerate(out_list):
        o.stop_gradient = False
        o._grad_node = node
        o._out_index = i
    return outs


def recompute_sequential(ctx, functions, *args, **kwargs):
    """paddle.distributed.fleet.utils.recompute_sequential parity: run a
    LayerList/Sequential in ``segments`` chunks, each chunk under
    :func:`recompute`. ``ctx`` keys: ``segments`` (default 1),
    ``preserve_rng_state``, ``policy`` (the rematerialization policy each
    segment compiles with — see :func:`recompute`)."""
    ctx = ctx or {}
    segments = max(int(ctx.get("segments", 1)), 1)
    preserve = bool(ctx.get("preserve_rng_state", True))
    policy = ctx.get("policy", "full")
    layers = list(functions)
    if not layers:
        raise ValueError("recompute_sequential: empty function list")
    per = max((len(layers) + segments - 1) // segments, 1)

    def run_chunk(chunk, *xs):
        out = chunk[0](*xs, **kwargs)
        for fn in chunk[1:]:
            out = fn(out, **kwargs) if not isinstance(out, (list, tuple)) \
                else fn(*out, **kwargs)
        return out

    out = args
    for s in range(0, len(layers), per):
        chunk = layers[s:s + per]
        # list and tuple outputs both unpack at segment boundaries, matching
        # run_chunk's in-segment behavior (a list-returning layer must not
        # change arity only when it lands on a chunk edge)
        xs = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        out = recompute(lambda *a, _c=chunk: run_chunk(_c, *a), *xs,
                        preserve_rng_state=preserve, policy=policy)
    return out
