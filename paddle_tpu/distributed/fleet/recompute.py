"""Activation recompute (gradient checkpointing).

Reference analog: fleet/recompute/recompute.py — a PyLayer that runs forward under
no_grad saving only inputs + RNG state, then re-runs it with grad during backward
(RNG replayed so dropout masks match).

Same structure here on the tape: forward under no_grad, a custom GradNode whose
backward re-executes the function eagerly (RNG state restored) and backpropagates
through the recomputed subgraph via autograd.grad — parameter grads accumulate as a
side effect exactly like the reference's inner backward. Under a to_static trace,
jax.checkpoint is the whole story and we simply mark the region.
"""
from __future__ import annotations

from typing import Any

from ...core import dispatch
from ...core import random as rnd
from ...core.autograd import GradNode, run_backward
from ...core.tensor import Tensor


def _flatten_tensors(obj, out):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _flatten_tensors(o, out)
    elif isinstance(obj, dict):
        for o in obj.values():
            _flatten_tensors(o, out)


def _recompute_traced(function, args, kwargs):
    """jax.checkpoint over the region inside an active trace.

    The function's INPUT tensors become the checkpoint arguments (their
    residuals are what remat drops); parameters captured by closure are traced
    as usual and recomputation re-reads them.

    Stateful side effects inside the region (dropout RNG chain advances, BN
    running-stat writes) are captured in a NESTED TraceContext and threaded
    OUT of the checkpoint as extra outputs — otherwise a remat-scope tracer
    would escape into the outer trace's buffer state (UnexpectedTracerError)."""
    import jax

    in_tensors: list = []
    _flatten_tensors((args, kwargs), in_tensors)
    arrays = tuple(t.value() for t in in_tensors)

    out_struct = {}

    def pure(arrs):
        saved = [t._data for t in in_tensors]
        inner_ctx = dispatch.TraceContext()
        dispatch.push_trace(inner_ctx)
        for t, a in zip(in_tensors, arrs):
            t._data = a
        try:
            out = function(*args, **kwargs)
            outs: list = []
            _flatten_tensors(out, outs)
            out_struct["single"] = isinstance(out, Tensor)
            out_struct["template"] = out
            out_struct["n_out"] = len(outs)
            out_struct["side_tensors"] = [t for t, _ in
                                          inner_ctx.buffer_updates]
            side_arrays = tuple(a for _, a in inner_ctx.buffer_updates)
            return tuple(o.value() for o in outs) + side_arrays
        finally:
            dispatch.pop_trace()
            inner_ctx.restore()
            for t, d in zip(in_tensors, saved):
                t._data = d

    out_arrays = jax.checkpoint(pure)(arrays)
    n_out = out_struct["n_out"]
    # re-emit the region's buffer updates into the OUTER trace so TrainStep /
    # to_static thread them as program state (post-checkpoint values)
    outer_ctx = dispatch.trace_ctx()
    for t, arr in zip(out_struct["side_tensors"], out_arrays[n_out:]):
        t._data = arr
        if outer_ctx is not None:
            outer_ctx.record_buffer_update(t, arr)
    if out_struct["single"]:
        return Tensor(out_arrays[0])
    # rebuild: replace each Tensor leaf of the template in order
    it = iter(out_arrays[:n_out])

    def rebuild(obj):
        if isinstance(obj, Tensor):
            return Tensor(next(it))
        if isinstance(obj, (list, tuple)):
            built = [rebuild(o) for o in obj]
            return type(obj)(built) if isinstance(obj, tuple) else built
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        return obj

    return rebuild(out_struct["template"])


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs) -> Any:
    """paddle.distributed.fleet.utils.recompute parity."""
    if dispatch.in_trace():
        # under jit/TrainStep tracing, apply jax.checkpoint so the compiled
        # program actually drops this region's residuals and recomputes them
        # in backward (a pass-through here would silently lose the memory
        # saving the user asked for)
        return _recompute_traced(function, args, kwargs)
    if not dispatch.is_grad_enabled():
        return function(*args, **kwargs)  # nothing to save anyway

    in_tensors: list = []
    _flatten_tensors((args, kwargs), in_tensors)
    diff_inputs = [t for t in in_tensors if not t.stop_gradient]

    rng_before = rnd.get_rng_state() if preserve_rng_state else None

    with dispatch.no_grad():
        outs = function(*args, **kwargs)

    single = isinstance(outs, Tensor)
    out_list = [outs] if single else [o for o in outs if isinstance(o, Tensor)]
    if not diff_inputs:
        return outs

    def _detach(obj, mapping):
        # sever the recomputed subgraph at the inputs: leaves here, so the inner
        # backward cannot walk (and release) the OUTER graph's nodes
        if isinstance(obj, Tensor):
            if id(obj) not in mapping:
                mapping[id(obj)] = Tensor(obj.value(),
                                          stop_gradient=obj.stop_gradient)
            return mapping[id(obj)]
        if isinstance(obj, (list, tuple)):
            mapped = [_detach(o, mapping) for o in obj]
            return type(obj)(mapped) if isinstance(obj, tuple) else mapped
        if isinstance(obj, dict):
            return {k: _detach(v, mapping) for k, v in obj.items()}
        return obj

    def bwd(primals, saved_outs, cotangents):
        rng_save = None
        if rng_before is not None:
            rng_save = rnd.get_rng_state()
            rnd.set_rng_state(rng_before)
        try:
            mapping = {}
            dargs = _detach(list(args), mapping)
            dkwargs = _detach(kwargs, mapping)
            detached_diff = [mapping[id(t)] for t in diff_inputs]
            with dispatch.enable_grad():
                re_out = function(*dargs, **dkwargs)
            re_list = [re_out] if isinstance(re_out, Tensor) else \
                [o for o in re_out if isinstance(o, Tensor)]
            cots = [Tensor(c) for c in cotangents[:len(re_list)]]
            # run_backward (not grad()): parameter grads must accumulate as a
            # side effect, like the reference's inner backward — grad() is
            # deliberately side-effect-free on non-input leaves
            for d in detached_diff:
                d._retain_grad_flag = True
            run_backward(re_list, cots)
            return [d._grad for d in detached_diff]
        finally:
            if rng_save is not None:
                rnd.set_rng_state(rng_save)

    node = GradNode(
        name="recompute", bwd_fn=bwd, mode="explicit",
        saved_primals=None, saved_outs=None,
        diff_idx=tuple(range(len(diff_inputs))),
        input_tensors=tuple(diff_inputs),
        out_metas=tuple((tuple(o.shape), o.dtype) for o in out_list))

    for i, o in enumerate(out_list):
        o.stop_gradient = False
        o._grad_node = node
        o._out_index = i
    return outs
