"""Filesystem abstraction for checkpoint/data staging.

Reference analog: the POSIX/HDFS fs + shell helpers
(paddle/fluid/framework/io/{fs,shell}.cc) surfaced as
paddle.distributed.fleet.utils.{LocalFS, HDFSClient}. Checkpoint writers and
dataset file lists go through this seam so jobs can point at either a local
disk or an HDFS namespace.

LocalFS is the real implementation; HDFSClient shells out to the `hadoop`
binary when present (same contract as the reference, which drives
`hadoop fs -...` through shell.cc) and raises a clear error otherwise.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

__all__ = ["FS", "LocalFS", "HDFSClient"]


class FS:
    """Interface (reference fs.py FS abstract base)."""

    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_file(self, path) -> bool:
        raise NotImplementedError

    def is_dir(self, path) -> bool:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        """Returns (dirs, files) directly under path."""
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def is_file(self, path) -> bool:
        return os.path.isfile(path)

    def is_dir(self, path) -> bool:
        return os.path.isdir(path)

    def is_exist(self, path) -> bool:
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite: bool = False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok: bool = True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        with open(path, "a"):
            os.utime(path)

    def need_upload_download(self) -> bool:
        return False

    def list_dirs(self, path) -> List[str]:
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """`hadoop fs` CLI wrapper (reference HDFSClient drives the same CLI via
    shell.cc). configs: {"fs.default.name": ..., "hadoop.job.ugi": ...}."""

    def __init__(self, hadoop_home: Optional[str] = None,
                 configs: Optional[dict] = None, time_out: int = 300):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._pre = []
        for k, v in (configs or {}).items():
            self._pre += ["-D", f"{k}={v}"]
        self._timeout = time_out

    def _run(self, *args) -> str:
        if not self._hadoop:
            raise RuntimeError(
                "no hadoop binary available; HDFSClient needs a Hadoop "
                "install (use LocalFS for local paths)")
        cmd = [self._hadoop, "fs"] + self._pre + list(args)
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=self._timeout)
        if out.returncode != 0:
            raise RuntimeError(f"hadoop {' '.join(args)} failed: "
                               f"{out.stderr[-500:]}")
        return out.stdout

    def ls_dir(self, path):
        dirs, files = [], []
        for line in self._run("-ls", path).splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path) -> bool:
        try:
            self._run("-test", "-e", path)
            return True
        except RuntimeError:
            return False

    def is_file(self, path) -> bool:
        try:
            self._run("-test", "-f", path)
            return True
        except RuntimeError:
            return False

    def is_dir(self, path) -> bool:
        return self.is_exist(path) and not self.is_file(path)

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, src, dst, overwrite: bool = False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def need_upload_download(self) -> bool:
        return True
