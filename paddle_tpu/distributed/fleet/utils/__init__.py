"""fleet.utils namespace (reference: python/paddle/distributed/fleet/utils)."""
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
from ..recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["FS", "LocalFS", "HDFSClient", "recompute", "recompute_sequential"]
