"""fleet — the hybrid-parallel training facade.

Reference analog: python/paddle/distributed/fleet/fleet.py (init:288 /
distributed_model / distributed_optimizer) dispatching wrappers by parallel mode
(fleet/model.py:30) over a HybridCommunicateGroup (topology.py:140).
"""
from __future__ import annotations

import os
from typing import Optional

from ..env import _maybe_init_multihost, get_hcg
from ..topology import AXES, CommunicateTopology, HybridCommunicateGroup
from .strategy import DistributedStrategy
from . import meta_parallel  # noqa: F401
from . import utils  # noqa: F401
from . import auto  # noqa: F401  (fleet.auto: planner + auto-parallel Engine)
from .meta_optimizers import HybridParallelOptimizer, DygraphShardingOptimizer
from .recompute import recompute, recompute_sequential  # noqa: F401

_fleet_state = {"strategy": None, "initialized": False}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """fleet.init: build the hybrid topology mesh (reference fleet.py:288,385)."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    import jax
    n = jax.device_count()
    degrees = {
        "data": int(hc.get("dp_degree", -1)),
        "pipe": int(hc.get("pp_degree", 1)),
        "sharding": int(hc.get("sharding_degree", 1)),
        "sep": int(hc.get("sep_degree", 1)),
        "model": int(hc.get("mp_degree", 1)),
    }
    if degrees["data"] in (0, -1):
        # infer dp to fill the machine (reference allows dp_degree=-1 = auto)
        rest = 1
        for k, v in degrees.items():
            if k != "data":
                rest *= max(v, 1)
        if n % rest != 0:
            raise ValueError(f"hybrid degrees {degrees} do not divide device "
                             f"count {n}")
        degrees["data"] = n // rest
    else:
        # explicit degrees must multiply out to the device count — never
        # silently override a user-set dp_degree (reference raises on mismatch)
        prod = 1
        for v in degrees.values():
            prod *= max(v, 1)
        if prod != n:
            raise ValueError(
                f"hybrid degrees {degrees} multiply to {prod} but "
                f"{n} devices are available; set dp_degree=-1 to infer dp")
    _maybe_init_multihost()
    topo = CommunicateTopology(AXES, [degrees[a] for a in AXES])
    HybridCommunicateGroup(topo)  # builds + registers the global mesh
    _fleet_state["strategy"] = strategy
    _fleet_state["initialized"] = True
    return None


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return get_hcg()


def distributed_model(model):
    """Wrap by parallel mode (reference fleet/model.py:30). Strategy toggles
    that transform the MODEL apply first: sync_batch_norm converts BN layers
    (reference distributed_strategy.proto sync_batch_norm -> convert pass);
    amp with use_pure_fp16 decorates to the O2 master-weight scheme."""
    strategy = _fleet_state.get("strategy")
    if strategy is not None and getattr(strategy, "sync_batch_norm", False):
        from ...nn import SyncBatchNorm
        model = SyncBatchNorm.convert_sync_batchnorm(model)
    if strategy is not None and getattr(strategy, "amp", False) and \
            strategy.amp_configs.get("use_pure_fp16", False):
        from ...amp import decorate
        model = decorate(models=model, level="O2")
    if strategy is not None and getattr(strategy, "recompute", False):
        # recompute strategy -> model config (reference recompute pass over
        # checkpoints; here the model wraps its own blocks through
        # fleet.recompute with the configured policy)
        cfg = strategy.recompute_configs or {}
        fn = getattr(model, "enable_recompute", None)
        if fn is not None:
            fn(cfg.get("granularity", "full"),
               interval=int(cfg.get("interval", 1)))
        else:
            import warnings
            warnings.warn(
                "DistributedStrategy.recompute is on but the model exposes "
                "no enable_recompute(granularity, interval); wrap block "
                "forwards in fleet.recompute(...) manually or the memory "
                "saving will silently not happen", RuntimeWarning)
    hcg = get_hcg()
    if hcg is None:
        init()
        hcg = get_hcg()
    mode = hcg.get_parallel_mode()
    mp = meta_parallel
    if mode == "pipeline":
        return mp.PipelineParallel(model, hcg, _fleet_state["strategy"])
    if mode == "sharding_parallel":
        return mp.ShardingParallel(model, hcg, _fleet_state["strategy"])
    if mode == "tensor_parallel":
        return mp.TensorParallel(model, hcg, _fleet_state["strategy"])
    from ..parallel import DataParallel
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    hcg = get_hcg()
    strategy = strategy or _fleet_state["strategy"] or DistributedStrategy()
    # strategy toggles compose innermost-first (reference meta-optimizer
    # ordering: dgc/localsgd/lars transform the inner optimizer, then
    # gradient_merge batches it, then sharding/hybrid places it)
    from .meta_optimizer_wrappers import (DGCOptimizer, GradientMergeOptimizer,
                                          LarsMomentumOptimizer,
                                          LocalSGDOptimizer)
    if getattr(strategy, "lars", False):
        optimizer = LarsMomentumOptimizer(optimizer,
                                          **(strategy.lars_configs or {}))
    if getattr(strategy, "dgc", False):
        optimizer = DGCOptimizer(optimizer)
    if getattr(strategy, "localsgd", False):
        optimizer = LocalSGDOptimizer(optimizer)
    if getattr(strategy, "gradient_merge", False):
        cfg = strategy.gradient_merge_configs or {}
        optimizer = GradientMergeOptimizer(optimizer,
                                           k_steps=cfg.get("k_steps", 1),
                                           avg=cfg.get("avg", True))
    if strategy.sharding or (hcg is not None
                             and hcg.get_sharding_parallel_world_size() > 1):
        cfg = getattr(strategy, "sharding_configs", None) or {}
        bucket = cfg.get("grad_bucket_bytes")
        if int(cfg.get("stage", 1)) >= 2:
            # stage >= 2: the ZeRO-2 optimizer additionally contracts grads
            # to come out of backward shard-sized (TrainStep compiles the
            # reduce-scatter into the scan body; the eager tape reshards at
            # accumulation)
            from ..sharding.group_sharded import _ShardingStage2Optimizer
            return _ShardingStage2Optimizer(optimizer, hcg, strategy,
                                            grad_bucket_bytes=bucket)
        return DygraphShardingOptimizer(optimizer, hcg, strategy,
                                        grad_bucket_bytes=bucket)
    return HybridParallelOptimizer(optimizer, hcg, strategy)


def worker_num() -> int:
    import jax
    return jax.process_count()


def worker_index() -> int:
    import jax
    return jax.process_index()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()


# ------------------------------------------------------------- PS-mode roles
# Env contract set by `launch --run_mode ps` (reference fleet PS mode:
# fleet.init(role) -> is_server()/init_server()/run_server() on pservers,
# trainer path otherwise).

_PS_SERVER = {"instance": None}


def is_server() -> bool:
    return os.environ.get("PADDLE_ROLE") == "PSERVER"


def is_worker() -> bool:
    """reference fleet.is_worker() — trainer role in a PS job (and the only
    role in collective jobs)."""
    return os.environ.get("PADDLE_ROLE", "TRAINER") == "TRAINER"


def server_endpoints() -> list:
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e for e in eps.split(",") if e]


def init_server(model=None, tables=None, lr: float = 1.0, seed: int = 0):
    """Build this pserver's tables and bind its PADDLE_PORT.

    Tables come either from `tables` ({name: SparseTable/DenseTable/shape})
    or from a model's parameters (DenseTable per param, seeded from the
    model's init so every role starts from identical weights)."""
    from ..ps import DenseTable, PSServer
    built = {}
    if tables:
        for name, t in tables.items():
            built[name] = t if not isinstance(t, (tuple, list)) \
                else DenseTable(t, lr=lr, seed=seed)
    if model is not None:
        for name, p in model.named_parameters():
            built[name] = DenseTable(tuple(p.shape), lr=lr,
                                     init=p.numpy().ravel())
    port = int(os.environ.get("PADDLE_PORT", "0"))
    _PS_SERVER["instance"] = PSServer(built, port=port)
    return _PS_SERVER["instance"]


def run_server():
    """Serve until terminated (reference fleet.run_server blocks)."""
    import signal
    import threading
    srv = _PS_SERVER["instance"]
    if srv is None:
        raise RuntimeError("call fleet.init_server() first")
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()


def stop_worker():
    pass  # trainer-side PS teardown: clients hold no server-side state


class Fleet:
    """Object form of the fleet facade (reference fleet.Fleet — the module
    functions above are the default instance's methods)."""

    def init(self, role_maker=None, is_collective=False, strategy=None):
        return init(role_maker, is_collective, strategy)

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def worker_num(self):
        return worker_num()

    def worker_index(self):
        return worker_index()

    def is_first_worker(self):
        return is_first_worker()

    def is_server(self):
        return is_server()

    def is_worker(self):
        return is_worker()

    def init_server(self, *args, **kwargs):
        return init_server(*args, **kwargs)

    def run_server(self):
        return run_server()

    def stop_worker(self):
        return stop_worker()

    @property
    def util(self):
        return UtilBase()


class UtilBase:
    """reference UtilBase: small cross-worker helpers."""

    def all_reduce(self, input, mode="sum"):
        import jax
        import numpy as np
        arr = np.asarray(input)
        if jax.process_count() <= 1:
            return arr            # single-controller: already global
        from jax.experimental import multihost_utils
        gathered = np.asarray(multihost_utils.process_allgather(arr))
        if mode == "sum":
            return gathered.sum(axis=0)
        if mode == "max":
            return gathered.max(axis=0)
        if mode == "min":
            return gathered.min(axis=0)
        raise ValueError(f"unsupported mode {mode!r}")

    def barrier(self):
        from .. import collective
        collective.barrier()

    def get_file_shard(self, files):
        import jax
        n, i = jax.process_count(), jax.process_index()
        return list(files)[i::n]


class Role:
    """reference role_maker.Role."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class PaddleCloudRoleMaker:
    """Env-contract role maker (reference PaddleCloudRoleMaker)."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective

    def _worker_num(self):
        return worker_num()

    def _worker_index(self):
        return worker_index()

    def _role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective)
        self._kw = kwargs


class MultiSlotDataGenerator:
    """PS data generator (reference fleet data_generator): subclass implements
    generate_sample; run_from_stdin/files emit the slot:feasign text format."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError

    def _format(self, sample):
        parts = []
        for name, feas in sample:
            parts.append(f"{len(feas)} " + " ".join(str(f) for f in feas))
        return " ".join(parts)

    def run_from_files(self, filelist, output_path):
        with open(output_path, "w") as out:
            for path in filelist:
                with open(path) as f:
                    for line in f:
                        gen = self.generate_sample(line.rstrip("\n"))
                        for sample in (gen() if callable(gen) else [gen]):
                            out.write(self._format(sample) + "\n")

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            gen = self.generate_sample(line.rstrip("\n"))
            for sample in (gen() if callable(gen) else [gen]):
                sys.stdout.write(self._format(sample) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass
