"""Elastic training manager.

Reference analog: python/paddle/distributed/fleet/elastic/manager.py:124
(ElasticManager: etcd leases + watches on the node prefix, scale-in/out
detection, endpoint rewrite, local trainer restart).

TPU-native: no etcd — the launcher's HTTP KV master doubles as the membership
store. Each node heartbeats its endpoint under <job>/elastic/; the manager
watches the peer set, and on a membership change invokes the registered
callback (typically: checkpoint + relaunch with the new world). On TPU pods,
preemption-aware checkpointing matters more than live rescale (slices are
restored whole), so the manager favors clean save-and-restart over in-place
endpoint rewrite.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional

from ..launch.master import KVClient

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Membership watcher + restart policy over the KV master."""

    def __init__(self, master_endpoint: str, job_id: str, my_endpoint: str,
                 np_target: int, heartbeat_interval: float = 2.0,
                 ttl: float = 6.0, scale_file: Optional[str] = None):
        self._kv = KVClient(master_endpoint)
        self._prefix = f"/{job_id}/elastic/"
        self._me = my_endpoint
        self._np = np_target
        self._interval = heartbeat_interval
        self._ttl = ttl
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._on_change: Optional[Callable[[List[str]], None]] = None
        self._last_peers: Optional[List[str]] = None
        self.status = ElasticStatus.HOLD
        # the restart wire back to the launch controller: on membership
        # change, the SURVIVING world size is written here and the elastic
        # controller relaunches at that np (its elastic_np control file —
        # the launcher exports the path as PADDLE_ELASTIC_NP_FILE). The
        # relaunched workers then resume from the pod-committed checkpoint,
        # resharded onto the new world (distributed/reshard).
        self._scale_file = scale_file if scale_file is not None \
            else os.environ.get("PADDLE_ELASTIC_NP_FILE")

    # ------------------------------------------------------------- lifecycle

    def register(self, on_change: Optional[Callable] = None):
        """Start heartbeating + watching (reference manager.start)."""
        self._on_change = on_change
        # hand the fleet-telemetry aggregator this membership view: the
        # collector cross-checks its liveness (stale publishers) against the
        # elastic peer set so the two can't silently disagree (a WARN in the
        # fleet stream names the split)
        try:
            from ...monitor import collector as _collector
            _collector.attach_elastic(self)
        except Exception:
            pass
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        watch = threading.Thread(target=self._watch_loop, daemon=True)
        self._threads = [hb, watch]
        hb.start()
        watch.start()

    def exit(self, completed: bool = True):
        self.status = (ElasticStatus.COMPLETED if completed
                       else ElasticStatus.EXIT)
        self._stop.set()
        # join the heartbeat first: a beat in flight would overwrite the
        # tombstone and make peers see a phantom live node for a full ttl
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=self._interval * 4)
        try:
            self._kv.put(self._prefix + self._me, "")  # tombstone
        except Exception:
            # the KV master is often ALREADY GONE when a job winds down (it
            # dies with node 0); shutdown must never throw over a courtesy
            # write — peers fall back to the ttl expiry to notice us missing
            pass

    # ----------------------------------------------------------------- loops

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            try:
                self._kv.put(self._prefix + self._me, str(time.time()))
            except Exception:
                pass  # transient master hiccup; next beat retries
            self._stop.wait(self._interval)

    def _live_peers(self) -> List[str]:
        now = time.time()
        peers = []
        for key, stamp in self._kv.get_prefix(self._prefix).items():
            if not stamp:
                continue  # tombstoned
            try:
                if now - float(stamp) <= self._ttl:
                    peers.append(key[len(self._prefix):])
            except ValueError:
                pass
        return sorted(peers)

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                peers = self._live_peers()
            except Exception:
                self._stop.wait(self._interval)
                continue  # never let a transient error kill the watcher
            if len(peers) >= self._np:
                # the target world has fully assembled at least once;
                # membership changes are meaningful from here on
                self._formed = True
            if self._last_peers is None:
                self._last_peers = peers
            elif peers != self._last_peers:
                # scale-in (dead node) or scale-out (join): reference rewrites
                # PADDLE_TRAINER_ENDPOINTS and restarts local trainers
                self._last_peers = peers
                if getattr(self, "_formed", False):
                    # only a FORMED world announces: during staggered
                    # startup the peer set grows through transient sizes,
                    # and announcing those would make the controller
                    # restart a perfectly healthy assembling pod
                    self.status = ElasticStatus.RESTART
                    self._announce_world(len(peers))
                    if self._on_change is not None:
                        self._on_change(peers)
            self._stop.wait(self._interval)

    def _announce_world(self, np_new: int):
        """Tell the launch controller to restart at the surviving world size
        (atomic write of its elastic_np control file). Best-effort: with no
        scale file configured, the controller's own liveness watch still
        scales in on worker death — this wire just makes scale-out and
        multi-node membership changes restart-driven too."""
        # the announcement also lands in the telemetry plane so a restart
        # decision is visible next to the stale-rank gauges it should match
        try:
            from ... import monitor
            monitor.emit("elastic_scale", np=int(np_new),
                         scale_file=self._scale_file or None)
        except Exception:
            pass
        if not self._scale_file or np_new < 1:
            return
        try:
            tmp = f"{self._scale_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(str(int(np_new)))
            os.replace(tmp, self._scale_file)
        except OSError:
            pass  # the controller keeps its current np until a writable beat

    # ------------------------------------------------------------------ info

    def world_ready(self) -> bool:
        return len(self._live_peers()) >= self._np

    def wait_for_world(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.world_ready():
                return True
            time.sleep(0.2)
        return False

    def peers(self) -> List[str]:
        return self._live_peers()
