"""fleet.auto — the auto-parallel surface under fleet (reference:
`from paddle.distributed.fleet import auto` re-exporting
python/paddle/distributed/auto_parallel). The planner (degree search over
the cost model) and Engine live in distributed.auto_parallel; this module
is the fleet-side name for them.
"""
from ..auto_parallel import (  # noqa: F401
    Engine, ModelStats, ParallelPlan, Planner, ProcessMesh, apply_plan,
    shard_op, shard_tensor, to_static,
)

__all__ = ["Engine", "ProcessMesh", "shard_tensor", "shard_op", "to_static",
           "Planner", "ParallelPlan", "ModelStats", "apply_plan"]
