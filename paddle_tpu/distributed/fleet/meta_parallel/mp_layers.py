"""Tensor-parallel layers.

Reference analog: fleet/layers/mpu/mp_layers.py (VocabParallelEmbedding:60,
ColumnParallelLinear, RowParallelLinear, ParallelCrossEntropy — 569 LoC) + mp_ops.py
PyLayer collectives (_c_identity/_mp_allreduce/_c_split/_c_concat, 888 LoC) and the
c_embedding / c_softmax_with_cross_entropy ops.

TPU-native: the layers hold GLOBAL-shape parameters placed with NamedShardings over the
"model" mesh axis; the forward is ordinary dense math. XLA's SPMD partitioner derives
the per-device compute and inserts the collectives the reference codes by hand:

  ColumnParallelLinear  W:[in, out@model]   y = xW      (no comm; gather on request)
  RowParallelLinear     W:[in@model, out]   y = xW      (contraction over the sharded
                                                         dim ⇒ psum, the reference's
                                                         mp_allreduce)
  VocabParallelEmbedding W:[vocab@model, h] row-gather  (masked-lookup+psum = the
                                                         reference's c_embedding)
  ParallelCrossEntropy  logits [..., vocab@model]       (softmax over a sharded axis ⇒
                                                         the reference's
                                                         c_softmax_with_cross_entropy)

All layers degrade to plain dense layers when the mesh has no model axis (mp degree 1),
so the same model file runs 1-chip and N-chip unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from .... import nn
from ....nn import functional as F
from ...env import get_mesh


def _model_axis_size(mesh) -> int:
    if mesh is None or "model" not in mesh.axis_names:
        return 1
    return mesh.shape["model"]


def _put(param, spec):
    mesh = get_mesh()
    if mesh is None or _model_axis_size(mesh) <= 1:
        return
    param._data = jax.device_put(param.value(), NamedSharding(mesh, spec))


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dim sharded over "model" (reference mp_layers.py:60)."""

    def __init__(self, num_embeddings: int, embedding_dim: int, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        mp = _model_axis_size(get_mesh())
        if num_embeddings % max(mp, 1) != 0:
            raise ValueError(f"vocab size {num_embeddings} not divisible by model "
                             f"parallel degree {mp}")
        self.embedding = nn.Embedding(num_embeddings, embedding_dim,
                                      weight_attr=weight_attr)
        _put(self.embedding.weight, P("model", None))

    @property
    def weight(self):
        return self.embedding.weight

    def forward(self, x):
        return self.embedding(x)


class ColumnParallelLinear(nn.Layer):
    """Linear with the output dim sharded over "model" (reference ColumnParallelLinear).

    gather_output=False keeps the activation sharded on its last dim (the fused
    column→row pattern); True re-replicates it (the reference's c_concat)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        mp = _model_axis_size(get_mesh())
        if out_features % max(mp, 1) != 0:
            raise ValueError(f"out_features {out_features} not divisible by model "
                             f"parallel degree {mp}")
        self.linear = nn.Linear(in_features, out_features, weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.gather_output = gather_output
        _put(self.linear.weight, P(None, "model"))
        if has_bias:
            _put(self.linear.bias, P("model"))

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return getattr(self.linear, "bias", None)

    def forward(self, x):
        y = self.linear(x)
        mesh = get_mesh()
        if _model_axis_size(mesh) > 1:
            spec = (P(*([None] * y.ndim)) if self.gather_output
                    else P(*([None] * (y.ndim - 1)), "model"))
            y._data = jax.device_put(y.value(), NamedSharding(mesh, spec))
        return y


class RowParallelLinear(nn.Layer):
    """Linear with the input dim sharded over "model" (reference RowParallelLinear).

    The xW contraction runs over the sharded dim, so SPMD emits the all-reduce the
    reference performs explicitly via mp_allreduce after the local matmul."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        mp = _model_axis_size(get_mesh())
        if in_features % max(mp, 1) != 0:
            raise ValueError(f"in_features {in_features} not divisible by model "
                             f"parallel degree {mp}")
        self.linear = nn.Linear(in_features, out_features, weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.input_is_parallel = input_is_parallel
        _put(self.linear.weight, P("model", None))

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return getattr(self.linear, "bias", None)

    def forward(self, x):
        mesh = get_mesh()
        if _model_axis_size(mesh) > 1 and not self.input_is_parallel:
            # re-place (not copy) the activation sharded on its contraction dim so
            # the matmul runs fully distributed (reference c_split); placement-only
            # mutation, autograd graph untouched
            spec = P(*([None] * (x.ndim - 1)), "model")
            if isinstance(x, Tensor):
                x._data = jax.device_put(x.value(), NamedSharding(mesh, spec))
        return self.linear(x)


class ParallelCrossEntropy(nn.Layer):
    """CE over vocab-sharded logits (reference ParallelCrossEntropy /
    c_softmax_with_cross_entropy): the log-sum-exp reduces over the sharded vocab
    dim, which SPMD turns into the psum pair the reference hand-codes."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.softmax_with_cross_entropy(input, label,
                                            ignore_index=self.ignore_index)
