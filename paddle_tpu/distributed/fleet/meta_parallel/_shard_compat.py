"""jax version compatibility for the manual-collective (shard_map) modules.

The ring/pipeline schedules are written against the current typed shard_map
API (`jax.shard_map` + `jax.lax.pcast(..., to="varying")`). Older jax
releases in some deployment images (0.4.x) keep shard_map in
`jax.experimental` and have no varying-type system at all — there, values
created inside the body are usable in cross-device collectives directly, so
the marking is a no-op.
"""
from __future__ import annotations

import jax

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map", "pvary"]


def pvary(x, axis_names):
    """Mark `x` as device-varying over `axis_names` inside shard_map.

    jax >= 0.7: `lax.pcast(..., to="varying")`; 0.5-0.6: `lax.pvary`;
    0.4.x: no varying types — identity.
    """
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axis_names))
    return x
