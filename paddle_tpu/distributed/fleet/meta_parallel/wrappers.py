"""TensorParallel / ShardingParallel model wrappers.

Reference analog: fleet/meta_parallel/tensor_parallel.py and sharding_parallel.py —
thin wrappers that broadcast initial states across their groups. Here "broadcast" is
placement: replicate what must agree, shard what the mode shards.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn.layer import Layer
from ...env import get_mesh


class InnerLayerDelegate:
    """Mixin: forward the state/parameter surface to self._layers (shared by
    every distributed wrapper — DataParallel-style facades, pipeline,
    group-sharded; previously duplicated 4x)."""

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class _MetaParallelBase(InnerLayerDelegate, Layer):
    def __init__(self, layers: Layer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(t) for t in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def _shard_input(self, t):
        mesh = get_mesh()
        if (not isinstance(t, Tensor) or mesh is None or t.ndim == 0
                or mesh.shape.get("data", 1) <= 1):
            return t
        spec = P("data", *([None] * (t.ndim - 1)))
        t._data = jax.device_put(t.value(), NamedSharding(mesh, spec))
        return t


class TensorParallel(_MetaParallelBase):
    """TP wrapper: mp layers already placed their own shards; everything else is
    replicated (the reference broadcasts non-TP params across the mp group)."""

    def _prepare_for_model(self):
        mesh = get_mesh()
        if mesh is None:
            return
        for _, p in self._layers.named_parameters():
            sh = getattr(p.value(), "sharding", None)
            already_sharded = (isinstance(sh, NamedSharding)
                               and any(s is not None for s in sh.spec))
            if not already_sharded:
                p._data = jax.device_put(
                    p.value(), NamedSharding(mesh, P(*([None] * p.ndim))))


class ShardingParallel(_MetaParallelBase):
    """ZeRO wrapper: parameter placement is unchanged here (stage 1/2 shard optimizer
    state and grads, handled by DygraphShardingOptimizer); stage 3 shards params via
    group_sharded_parallel."""
    pass
