"""Pipeline model description & segmentation.

Reference analog: fleet/meta_parallel/parallel_layers/pp_layers.py:208 — PipelineLayer
takes a LayerDesc list, segments it into stages (by layer count or param count),
instantiates only the local stage's layers, and tracks shared-weight groups (tied
embeddings).

TPU-native: all stages exist in the one process; "belonging to stage i" is placement —
each stage's parameters live on the submesh at pipe coordinate i. Stage boundaries are
where activations get re-placed (the compiled equivalent of the reference's p2p
send/recv over NICs is an ICI device-to-device copy that jax dispatches
asynchronously).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....nn.layer import Layer, LayerList
from ...env import get_mesh


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a nn.Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied weights across stages (reference: tied embeddings in GPT)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight",
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def _stage_submesh(mesh: Mesh, stage: int) -> Optional[Mesh]:
    """The global mesh restricted to pipe coordinate `stage` (pipe axis dropped)."""
    if mesh is None or "pipe" not in mesh.axis_names or mesh.shape["pipe"] == 1:
        return None
    pipe_idx = mesh.axis_names.index("pipe")
    devices = np.take(mesh.devices, stage, axis=pipe_idx)
    names = tuple(n for n in mesh.axis_names if n != "pipe")
    return Mesh(devices, names)


class PipelineLayer(Layer):
    """Reference pp_layers.py:208. seg_method: "uniform" (layer count) or
    "layer:<ClassName>" (split at occurrences of a class, e.g. transformer blocks)."""

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 recompute_ctx=None, num_virtual_pipeline_stages: int = 1):
        super().__init__()
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        mesh = get_mesh()
        if num_stages is None:
            num_stages = mesh.shape["pipe"] if (mesh is not None and
                                                "pipe" in mesh.axis_names) else 1
        self._num_stages = num_stages
        self._descs = list(layers)
        self._shared_layers = {}

        # build all layers (single-controller holds every stage)
        built: List[Layer] = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared_layers:
                    layer = self._shared_layers[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared_layers[d.layer_name] = layer
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        self.run_function = LayerList(built)
        self._segment(seg_method)
        self._place_stages()

    # ------------------------------------------------------------- segmentation

    def _segment(self, seg_method: str):
        n = len(self.run_function)
        stages = self._num_stages
        if seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, l in enumerate(self.run_function)
                     if type(l).__name__ == cls_name]
            if len(marks) < stages:
                raise ValueError(f"cannot split {len(marks)} x {cls_name} into "
                                 f"{stages} stages")
            per = len(marks) // stages
            bounds = [0]
            for s in range(1, stages):
                bounds.append(marks[s * per])
            bounds.append(n)
        else:
            per = (n + stages - 1) // stages
            bounds = [min(i * per, n) for i in range(stages)] + [n]
        self._stage_bounds = bounds  # stage s = layers [bounds[s], bounds[s+1])

    def stage_of_layer(self, idx: int) -> int:
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    def _place_stages(self):
        mesh = get_mesh()
        if mesh is None or self._num_stages <= 1:
            return
        shared_ids = {id(l) for l in self._shared_layers.values()}
        for i, layer in enumerate(self.run_function):
            if id(layer) in shared_ids:
                continue  # tied layers stay replicated over pipe (reference keeps
                # a copy on both stages + allreduces their grads)
            sub = _stage_submesh(mesh, self.stage_of_layer(i))
            if sub is None:
                continue
            for _, p in layer.named_parameters():
                p._data = jax.device_put(
                    p.value(), NamedSharding(sub, P(*([None] * p.ndim))))
            for _, b in layer.named_buffers():
                b._data = jax.device_put(
                    b.value(), NamedSharding(sub, P(*([None] * b.ndim))))

    # ------------------------------------------------------------- forward

    def forward(self, x):
        from ....core.tensor import Tensor
        mesh = get_mesh()
        prev_stage = 0
        for i, layer in enumerate(self.run_function):
            s = self.stage_of_layer(i)
            if s != prev_stage and mesh is not None and self._num_stages > 1:
                # stage boundary: re-place the activation onto the next stage's
                # submesh (the ICI p2p analog of p2p_communication.py send/recv)
                sub = _stage_submesh(mesh, s)
                if sub is not None and isinstance(x, Tensor):
                    x._data = jax.device_put(
                        x.value(), NamedSharding(sub, P(*([None] * x.ndim))))
                prev_stage = s
            if self._recompute_interval > 0 and i % self._recompute_interval == 0 \
                    and self.training:
                from ..recompute import recompute
                x = recompute(layer, x)
            else:
                x = layer(x)
        return x

    def get_shared_layer(self, key):
        return self._shared_layers[key]


class _FuncLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)
