"""Compiled pipeline schedule: shard_map + ppermute ring over the "pipe" axis.

Reference analog: PipelineParallel.forward_backward_pipeline (1F1B,
fleet/meta_parallel/pipeline_parallel.py:117) and PipelineParallelWithInterleave
(:461, virtual stages) with p2p_communication.py send/recv. There, a Python
scheduler issues per-microbatch sends/recvs between rank processes.

TPU-native: the ENTIRE schedule — fill, steady state, drain, and (with
num_virtual > 1) the interleaved/circular rotation — is one XLA executable:
a lax.scan over schedule ticks inside shard_map, with lax.ppermute moving
activations stage→stage over ICI. Every device computes every tick (bubbles are
masked), the backward pipeline falls out of jax.grad reversing the scan+permutes,
and XLA overlaps the permute DMA of tick t with compute of tick t+1 — the
overlap the reference hand-builds with batch_isend_irecv.

Constraints (same as any ring pipeline): stage_fn must be shape-preserving
([mb, ...] -> [mb, ...]) so activations can rotate; embedding/head live outside
the ring. Microbatch count M must be >= stage count S when num_virtual > 1
(wrap-around latency M-S+1 must be positive).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._shard_compat import pvary, shard_map

__all__ = ["pipeline_apply", "CompiledPipeline"]


def _ring_body(w_local, xs, stage_fn, S: int, M: int, V: int, axis: str):
    """Runs on ONE device (inside shard_map). w_local leaves: [1, V, ...]."""
    s = jax.lax.axis_index(axis)
    w_local = jax.tree_util.tree_map(lambda l: l[0], w_local)  # [V, ...]
    T = V * M + S - 1
    buf = jnp.zeros((M,) + xs.shape[1:], xs.dtype)      # per-microbatch inbox
    outputs = jnp.zeros((M,) + xs.shape[1:], xs.dtype)
    # the carry holds per-DEVICE state (each stage's inbox differs), so mark it
    # varying over the pipe axis for the typed shard_map carry check
    buf = pvary(buf, (axis,))
    outputs = pvary(outputs, (axis,))

    def tick(carry, t):
        buf, outputs = carry
        pos = t - s
        valid = (pos >= 0) & (pos < V * M)
        v = jnp.clip(pos // M, 0, V - 1)
        m = jnp.clip(pos % M, 0, M - 1)
        first_feed = (s == 0) & (v == 0)
        x_in = jnp.where(first_feed, xs[m], buf[m])
        w_v = jax.tree_util.tree_map(lambda l: l[v], w_local)
        y = stage_fn(w_v, x_in)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # final global stage (device S-1, virtual V-1) writes the output slot
        is_out = valid & (s == S - 1) & (v == V - 1)
        outputs = outputs.at[m].set(jnp.where(is_out, y, outputs[m]))
        # rotate: stage s -> s+1 (cyclic; the wrap edge feeds virtual stage v+1)
        y_recv = jax.lax.ppermute(y, axis, [(i, (i + 1) % S) for i in range(S)])
        prev = (s - 1) % S
        pos_in = t - prev
        v_in = pos_in // M
        m_in = jnp.clip(pos_in % M, 0, M - 1)
        valid_in = (pos_in >= 0) & (pos_in < V * M) & \
            ~((s == 0) & (v_in == V - 1))   # drop the ring's final outputs
        buf = buf.at[m_in].set(jnp.where(valid_in, y_recv, buf[m_in]))
        return (buf, outputs), None

    (buf, outputs), _ = jax.lax.scan(tick, (buf, outputs), jnp.arange(T))
    # only device S-1 holds real outputs (others wrote zeros) — psum replicates
    return jax.lax.psum(outputs, axis)


def pipeline_apply(stage_params: Any, xs: jnp.ndarray,
                   stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   mesh: Mesh, axis: str = "pipe", num_virtual: int = 1):
    """Apply S*num_virtual pipeline stages to M microbatches, compiled.

    stage_params: pytree with leading dims [S*num_virtual, ...] per leaf
    (global stage g = v*S + s runs as virtual stage v on device s).
    xs: [M, mb, ...] microbatched inputs (replicated).
    Returns [M, mb, ...] outputs, replicated.
    """
    S = mesh.shape[axis]
    M = int(xs.shape[0])
    V = int(num_virtual)
    if V > 1 and M < S:
        raise ValueError(f"interleaved pipeline needs micro-batches >= stages "
                         f"(got M={M} < S={S})")

    def split_vs(leaf):
        # [V*S, ...] -> [S, V, ...]: device s owns global stages s, S+s, ...
        lead = leaf.shape[0]
        if lead != V * S:
            raise ValueError(f"stage_params leading dim {lead} != "
                             f"num_virtual*stages {V * S}")
        return jnp.swapaxes(leaf.reshape((V, S) + leaf.shape[1:]), 0, 1)

    w = jax.tree_util.tree_map(split_vs, stage_params)
    w_specs = jax.tree_util.tree_map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), w)
    fn = shard_map(
        partial(_ring_body, stage_fn=stage_fn, S=S, M=M, V=V, axis=axis),
        mesh=mesh, in_specs=(w_specs, P(*([None] * xs.ndim))), out_specs=P())
    return fn(w, xs)


class CompiledPipeline:
    """Convenience wrapper: jit the ring once per (shapes, loss_fn) and expose
    forward(+loss) and grads — a compiled train-side replacement for the
    reference's interleaved 1F1B scheduler."""

    def __init__(self, stage_fn, mesh: Optional[Mesh] = None, axis: str = "pipe",
                 num_virtual: int = 1, loss_fn: Optional[Callable] = None):
        from ...env import get_mesh
        self._mesh = mesh if mesh is not None else get_mesh()
        self._axis = axis
        self._V = num_virtual
        self._stage_fn = stage_fn
        self._loss_fn = loss_fn
        self._fwd = jax.jit(self._forward)
        self._grad = jax.jit(jax.value_and_grad(self._loss)) \
            if loss_fn is not None else None

    def _forward(self, stage_params, xs):
        return pipeline_apply(stage_params, xs, self._stage_fn, self._mesh,
                              self._axis, self._V)

    def _loss(self, stage_params, xs, *labels):
        out = self._forward(stage_params, xs)
        return self._loss_fn(out, *labels)

    def forward(self, stage_params, xs):
        return self._fwd(stage_params, xs)

    def loss_and_grad(self, stage_params, xs, *labels):
        if self._grad is None:
            raise ValueError("CompiledPipeline built without loss_fn")
        return self._grad(stage_params, xs, *labels)
