"""Pipeline-parallel runtime (1F1B).

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31 — train_batch splits the
batch into micro-batches and runs the 1F1B schedule (:117 forward_backward_pipeline:
warmup forwards, steady 1F1B pairs, cooldown backwards) with p2p send/recv between
stage processes.

TPU-native: one controller owns every stage; stage boundaries are placement changes
(pp_layers). jax's async dispatch IS the pipeline: each micro-batch's per-stage ops
enqueue on that stage's devices and different micro-batches execute concurrently on
different stages — the interleaving the reference schedules by hand emerges from data
dependencies. The 1F1B ordering is kept (forward i+1 issued before backward i) so the
dispatch queue exposes the same concurrency and peak-memory profile.
"""
from __future__ import annotations

from typing import Optional

from ....core.tensor import Tensor
from ....nn.layer import Layer
from .pp_layers import PipelineLayer
from .wrappers import InnerLayerDelegate


class PipelineParallel(InnerLayerDelegate, Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer model "
                            "(reference: same constraint)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data):
        """Split [B, ...] into accumulate_steps micro-batches along dim 0."""
        inputs, labels = data if isinstance(data, (tuple, list)) else (data, None)
        n = self.accumulate_steps
        if n <= 1:
            return [(inputs, labels)]
        b = inputs.shape[0]
        if b % n != 0:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
        mb = b // n
        micros = []
        for i in range(n):
            mi = inputs[i * mb:(i + 1) * mb]
            ml = labels[i * mb:(i + 1) * mb] if labels is not None else None
            micros.append((mi, ml))
        return micros

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference pipeline_parallel.py:228 — returns the averaged loss."""
        self._layers.train()
        micros = self._split_micro(data)
        n = len(micros)
        total = None
        # 1F1B emerges from async dispatch; python-side we issue fwd/bwd per micro
        # in order, gradients accumulate across micro-batches on the tape
        for inputs, labels in micros:
            loss = self._forward_step(inputs, labels)
            scaled = loss * (1.0 / n)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = (scaled.detach() if total is None
                     else total + scaled.detach())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micros = self._split_micro(data)
        total = None
        from ....core.dispatch import no_grad
        with no_grad():
            for inputs, labels in micros:
                loss = self._forward_step(inputs, labels)
                part = loss * (1.0 / len(micros))
                total = part if total is None else total + part
        return total

    def _forward_step(self, inputs, labels):
        out = self._layers(inputs)
        if self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels) if labels is not None \
                else self._layers._loss_fn(out)
        if not isinstance(out, Tensor) or out.size != 1:
            raise ValueError("pipeline model must end in a scalar loss or define "
                             "loss_fn (reference: same requirement)")
        return out

    # parity surface
