"""Pipeline-parallel runtime.

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31 — train_batch
splits the batch into micro-batches and runs the 1F1B schedule (:117
forward_backward_pipeline: warmup forwards, steady 1F1B pairs, cooldown
backwards) with p2p send/recv between stage processes.

TPU-native: ONE pipeline stack. When the PipelineLayer's body is a run of
identical shape-preserving blocks (transformer stacks are), `train_batch`
routes through the COMPILED ring schedule (compiled_pipeline.py: shard_map +
ppermute over the pipe axis, the whole fill/steady/drain schedule in one XLA
executable) — prologue (embedding) and epilogue (norm/head/loss) compile into
the same executable, and the backward pipeline falls out of jax.grad
reversing the scan+permutes. When the body is irregular, train_batch falls
back to a sequential per-microbatch loop with gradient accumulation — which
is NOT a 1F1B schedule and overlaps nothing; it is the correctness fallback,
the compiled ring is the performance path.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....core import dispatch
from ....core.tensor import Tensor
from ....nn.layer import Layer
from .pp_layers import PipelineLayer
from .wrappers import InnerLayerDelegate


def _param_signature(layer: Layer) -> Tuple:
    return (type(layer).__name__,
            tuple((name, tuple(p.shape), str(p.dtype))
                  for name, p in layer.named_parameters()))


def _functional_apply(layers: List[Layer], params: List, arrays, x):
    """Run `layers` with `arrays` substituted for their parameters — pure, so
    it can live inside jit/shard_map (TrainStep's trace trick)."""
    saved = [p._data for p in params]
    ctx = dispatch.TraceContext()
    dispatch.push_trace(ctx)
    try:
        for p, a in zip(params, arrays):
            p._data = a
        t = Tensor(x)
        for l in layers:
            t = l(t)
        return t.value()
    finally:
        dispatch.pop_trace()
        ctx.restore()
        for p, d in zip(params, saved):
            p._data = d


class PipelineParallel(InnerLayerDelegate, Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer model "
                            "(reference: same constraint)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.total_loss = None
        self._ring = None           # (jitted loss_and_grad, metadata)
        self._ring_checked = False

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # ------------------------------------------------------- compiled route

    def _find_ring(self):
        """Locate the longest contiguous run of structurally identical
        parameterized layers whose count is a multiple of the stage count —
        the ring body; everything before is the prologue, after the epilogue.
        Returns None when the model shape doesn't admit the compiled ring."""
        from ...env import get_mesh
        mesh = get_mesh()
        S = self._layers._num_stages
        if mesh is None or "pipe" not in mesh.axis_names or S <= 1 \
                or mesh.shape["pipe"] != S:
            return None
        seq = list(self._layers.run_function)
        sigs = [_param_signature(l) for l in seq]
        best = (0, 0)                    # (start, length)
        i = 0
        while i < len(seq):
            if not sigs[i][1]:           # parameterless: cannot anchor a run
                i += 1
                continue
            j = i
            while j < len(seq) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        start, length = best
        L = (length // S) * S            # ring takes a stage-divisible count
        if L < S or L == 0:
            return None
        # the ring bakes buffers/RNG state in as constants (unlike TrainStep,
        # which threads buffer updates through the executable): models with
        # live dropout or stateful buffers (BN) must keep the eager fallback
        # or dropout masks would repeat every step
        from .... import nn as _nn
        drop_types = tuple(t for t in (
            getattr(_nn, "Dropout", None), getattr(_nn, "Dropout2D", None),
            getattr(_nn, "Dropout3D", None),
            getattr(_nn, "AlphaDropout", None)) if t is not None)

        def _ring_safe(layer):
            drop_attrs = ("p", "_p", "dropout", "dropout_p", "attn_dropout",
                          "dropout_rate", "dropout_prob", "drop_rate")
            for sub in [layer] + [l for _, l in layer.named_sublayers()]:
                if isinstance(sub, drop_types) and float(
                        getattr(sub, "p", getattr(sub, "_p", 0))) > 0:
                    return False
                # functional dropout: layers stash the rate as a float attr
                # (MultiHeadAttention.dropout etc.) and draw RNG per call
                for a in drop_attrs:
                    v = getattr(sub, a, None)
                    if isinstance(v, float) and v > 0:
                        return False
                if list(sub.named_buffers()):
                    return False
            return True

        if not all(_ring_safe(l) for l in self._layers.run_function):
            return None
        # keep trailing extras in the epilogue
        return start, L, S

    def _build_ring(self):
        """Compile (prologue -> ring -> epilogue -> loss) into one
        value_and_grad executable over (ring, prologue, epilogue) params."""
        found = self._find_ring()
        if found is None:
            return None
        from .compiled_pipeline import pipeline_apply
        from ...env import get_mesh
        start, L, S = found
        mesh = get_mesh()
        V = L // S
        seq = list(self._layers.run_function)
        blocks = seq[start:start + L]
        prologue = seq[:start]
        epilogue = seq[start + L:]
        loss_fn = self._layers._loss_fn

        template = blocks[0]
        tmpl_params = [p for _, p in template.named_parameters()]

        def collect(layers):
            seen, out = set(), []
            for l in layers:
                for _, p in l.named_parameters():
                    if id(p) not in seen:       # tied weights appear once
                        seen.add(id(p))
                        out.append(p)
            return out

        pro_params = collect(prologue)
        epi_params = collect(epilogue)

        def stage_fn(w_leaves, x):
            return _functional_apply([template], tmpl_params, w_leaves, x)

        def full_loss(ring_w, pro_w, epi_w, xs, labels):
            # xs: [M, mb, ...] raw microbatches

            def pro_one(x):
                return _functional_apply(prologue, pro_params, pro_w, x)

            h = jax.vmap(pro_one)(xs) if prologue else xs
            h = pipeline_apply(tuple(ring_w), h, stage_fn, mesh, "pipe", V)

            def epi_one(hm, lm):
                out = _functional_apply(epilogue, epi_params, epi_w, hm)
                if loss_fn is not None:
                    if lm is None:
                        return loss_fn(Tensor(out)).value()
                    return loss_fn(Tensor(out), Tensor(lm)).value()
                if int(np.prod(out.shape)) != 1:
                    raise ValueError(
                        "pipeline model must end in a scalar loss or define "
                        "loss_fn (reference: same requirement)")
                return out.reshape(())

            if labels is not None:
                losses = jax.vmap(epi_one)(h, labels)
                return jnp.mean(losses)
            return jnp.mean(jax.vmap(lambda hm: epi_one(hm, None))(h))

        jitted = jax.jit(jax.value_and_grad(full_loss, argnums=(0, 1, 2)))
        block_params = [[p for _, p in blk.named_parameters()]
                        for blk in blocks]
        meta = dict(blocks=blocks, tmpl_params=tmpl_params,
                    block_params=block_params,
                    pro_params=pro_params, epi_params=epi_params, L=L, S=S)
        return jitted, meta

    def _try_ring(self):
        if not self._ring_checked:
            self._ring_checked = True
            try:
                self._ring = self._build_ring()
            except Exception:
                self._ring = None
        return self._ring

    def _ring_step(self, inputs, labels, optimizer, scaler):
        jitted, meta = self._ring
        n = self.accumulate_steps
        x = inputs.value() if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        lab = labels.value() if isinstance(labels, Tensor) else \
            (jnp.asarray(labels) if labels is not None else None)
        b = x.shape[0]
        if b % n != 0:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
        xs = x.reshape((n, b // n) + x.shape[1:])
        ls = lab.reshape((n, b // n) + lab.shape[1:]) if lab is not None else None

        # refresh weights from the live parameters (optimizer steps mutate
        # them between batches). Per-stage params live on disjoint
        # submeshes; re-place them REPLICATED over the full mesh
        # (device-side reshard, no host roundtrip) so one jit sees a
        # consistent device set.
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ...env import get_mesh
        rep = NamedSharding(get_mesh(), P())

        def to_full(arr):
            return jax.device_put(arr, rep)

        stacked = []
        for k in range(len(meta["tmpl_params"])):
            stacked.append(jnp.stack(
                [to_full(bp[k].value()) for bp in meta["block_params"]],
                axis=0))
        pro_w = [to_full(p.value()) for p in meta["pro_params"]]
        epi_w = [to_full(p.value()) for p in meta["epi_params"]]

        loss, (g_ring, g_pro, g_epi) = jitted(tuple(stacked), pro_w, epi_w,
                                              xs, ls)
        # scatter grads back onto the real Parameters — re-placed onto each
        # param's own (stage-submesh) sharding so the optimizer's fused
        # update sees matching device sets; then step exactly as in eager
        def land(p, g):
            sh = getattr(p.value(), "sharding", None)
            if sh is not None:
                g = jax.device_put(g, sh)   # device-side reshard
            p._accumulate_grad(g)

        with dispatch.no_grad():
            for k, g in enumerate(g_ring):
                for bi, bp in enumerate(meta["block_params"]):
                    land(bp[k], g[bi])
            for p, g in zip(meta["pro_params"], g_pro):
                land(p, g)
            for p, g in zip(meta["epi_params"], g_epi):
                land(p, g)
        if scaler is not None and getattr(scaler, "_enable", True):
            # the ring computes loss/grads in full precision (no fp16
            # scaling needed), but an ENABLED scaler's found_inf contract
            # still holds: skip the step when any grad is non-finite.
            # A disabled scaler (bf16 default) never gates the step and
            # pays no per-step finiteness sync.
            flat = jax.tree_util.tree_leaves((g_ring, g_pro, g_epi))
            finite = bool(jnp.all(jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in flat])))
            scaler._found_inf = not finite
            scaler._cache_founf_inf = not finite  # reference attr name (sic)
            if finite:
                optimizer.step()
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        return Tensor(loss)

    # ----------------------------------------------------------- train/eval

    def _split_micro(self, data):
        """Split [B, ...] into accumulate_steps micro-batches along dim 0."""
        inputs, labels = data if isinstance(data, (tuple, list)) else (data, None)
        n = self.accumulate_steps
        if n <= 1:
            return [(inputs, labels)]
        b = inputs.shape[0]
        if b % n != 0:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
        mb = b // n
        micros = []
        for i in range(n):
            mi = inputs[i * mb:(i + 1) * mb]
            ml = labels[i * mb:(i + 1) * mb] if labels is not None else None
            micros.append((mi, ml))
        return micros

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference pipeline_parallel.py:228 — returns the averaged loss."""
        self._layers.train()
        if self._try_ring() is not None:
            _, meta = self._ring
            n = self.accumulate_steps
            if meta["L"] > meta["S"] and n < meta["S"]:
                # deliberate config diagnostic: must reach the user, not the
                # fallback swallow below
                raise ValueError(
                    f"interleaved ring needs accumulate_steps >= stages "
                    f"({meta['S']}); got {n} (reference: micro-batches >= "
                    f"stages)")
            inputs, labels = data if isinstance(data, (tuple, list)) \
                else (data, None)
            if inputs.shape[0] % n != 0:
                # genuine config/data error — same message the eager path
                # raises; must NOT permanently disable the ring below
                raise ValueError(f"batch {inputs.shape[0]} not divisible "
                                 f"by accumulate_steps {n}")
            try:
                loss = self._ring_step(inputs, labels, optimizer, scaler)
            except (ValueError, TypeError) as e:
                # trace-time shape/contract failure (jit compiles lazily at
                # the first call; jax raises TypeError for tracer leaks):
                # permanently fall back to the eager loop, which re-raises
                # genuine model errors with the right message
                import warnings
                warnings.warn(f"compiled ring disabled, using the eager "
                              f"fallback (no stage overlap): {e}")
                self._ring = None
                # _ring_step may have landed (partial) grads before failing;
                # the eager loop below re-runs the same batch, so start clean
                # or the batch would be double-applied
                optimizer.clear_grad()
            else:
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return loss
        micros = self._split_micro(data)
        n = len(micros)
        total = None
        # correctness fallback: sequential per-microbatch fwd+bwd with grad
        # accumulation (no stage overlap — the ring above is the fast path)
        for inputs, labels in micros:
            loss = self._forward_step(inputs, labels)
            scaled = loss * (1.0 / n)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = (scaled.detach() if total is None
                     else total + scaled.detach())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micros = self._split_micro(data)
        total = None
        from ....core.dispatch import no_grad
        with no_grad():
            for inputs, labels in micros:
                loss = self._forward_step(inputs, labels)
                part = loss * (1.0 / len(micros))
                total = part if total is None else total + part
        return total

    def _forward_step(self, inputs, labels):
        out = self._layers(inputs)
        if self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels) if labels is not None \
                else self._layers._loss_fn(out)
        if not isinstance(out, Tensor) or out.size != 1:
            raise ValueError("pipeline model must end in a scalar loss or define "
                             "loss_fn (reference: same requirement)")
        return out

    # parity surface
