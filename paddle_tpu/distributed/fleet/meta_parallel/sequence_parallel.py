"""Sequence/context parallelism over the "sep" mesh axis.

The reference has NO sequence parallelism (SURVEY.md §2.4: repo-wide grep empty);
its long-context story stops at flash-attention kernels
(phi/kernels/flash_attn_kernel.h). This module fills that declared capability gap
the TPU-native way:

- `ring_attention(q, k, v)`: causal attention with the SEQUENCE dim sharded over
  "sep". Each device keeps its Q shard; K/V shards rotate around the ring via
  lax.ppermute (one hop per step, over ICI), and partial softmax results combine
  with the running log-sum-exp trick — flash attention's online softmax, applied
  across devices. Memory per device: O(S/sep * S/sep) per block instead of O(S²);
  activations elsewhere stay sharded [B, S/sep, H].
- `shard_sequence` / `gather_sequence`: place/unplace the activation sequence
  dim on the sep axis (SP region entry/exit).

Composability: the ring's shard_map specs are derived from the INPUT placements,
so batch sharded over "data" and heads sharded over "model" (TP) stay sharded
through the ring; only the sequence dim participates in the rotation.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...env import get_mesh
from ._shard_compat import pvary, shard_map

__all__ = ["ring_attention", "shard_sequence", "gather_sequence"]


def _ring_attn_local(q, k, v, sm_scale: float, S: int, axis: str,
                     vary: tuple = ()):
    """Per-device body: q,k,v [B, L, H, D] (L = local seq shard).

    Device r owns query block r and initially key block r. At ring step j it
    holds key block (r - j) mod S. Causal masking happens at BLOCK granularity:
    a key block strictly newer than the query block contributes nothing; the
    diagonal block applies the elementwise causal mask.
    """
    r = jax.lax.axis_index(axis)
    B, L, H, D = q.shape
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,H,L,D]

    def step(carry, j):
        k_cur, v_cur, acc, lse = carry
        kb = (r - j) % S                             # key block id this step
        kt = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * sm_scale
        # block-causal mask: query global pos = r*L + i, key pos = kb*L + t
        qpos = r * L + jnp.arange(L)[:, None]
        kpos = kb * L + jnp.arange(L)[None, :]
        mask = qpos >= kpos
        logits = jnp.where(mask, logits, -jnp.inf)
        blk_lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B,H,L]
        # renormalize the running accumulator (flash online softmax across devices)
        new_lse = jnp.logaddexp(lse, blk_lse)
        probs = jnp.exp(logits - new_lse[..., None])
        probs = jnp.where(jnp.isfinite(new_lse)[..., None], probs, 0.0)
        scale_old = jnp.exp(lse - new_lse)
        scale_old = jnp.where(jnp.isfinite(new_lse), scale_old, 0.0)
        acc = acc * scale_old[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                      probs, vt)
        # rotate K/V one hop: device i's block moves to i+1
        perm = [(i, (i + 1) % S) for i in range(S)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, acc, new_lse), None

    # the carry varies over every axis the inputs are split on (sep + any
    # batch/head shardings that pass through), per typed-shard_map rules
    vary_all = tuple(dict.fromkeys((axis,) + tuple(vary)))
    acc0 = pvary(jnp.zeros((B, H, L, D), jnp.float32), vary_all)
    lse0 = pvary(jnp.full((B, H, L), -jnp.inf, jnp.float32), vary_all)
    (k_f, v_f, acc, lse), _ = jax.lax.scan(
        step, (k, v, acc0, lse0), jnp.arange(S))
    out = jnp.swapaxes(acc, 1, 2)                    # [B,L,H,D]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Optional[Mesh] = None, axis: str = "sep",
                   sm_scale: Optional[float] = None):
    """Causal ring attention; q,k,v: [B, S_global, H, D] with the sequence dim
    sharded over `axis` (global arrays in, global arrays out)."""
    mesh = mesh if mesh is not None else get_mesh()
    S = mesh.shape[axis]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if S == 1:
        # degenerate: plain causal attention
        return _plain_causal(q, k, v, sm_scale)

    def spec_like(arr):
        # preserve the caller's batch ("data") and head ("model") shardings —
        # only the sequence dim (1) joins the ring
        base = [None, None, None, None]
        spec_t = getattr(getattr(arr, "sharding", None), "spec", None)
        if spec_t is not None:
            for i, s in enumerate(tuple(spec_t)[:4]):
                base[i] = s
        base[1] = axis
        return P(*base)

    sq, sk, sv = spec_like(q), spec_like(k), spec_like(v)
    vary = tuple({a for sp in (sq, sk, sv) for dim in tuple(sp)
                  for a in ((dim,) if isinstance(dim, str) else (dim or ()))
                  if a != axis})
    fn = shard_map(partial(_ring_attn_local, sm_scale=sm_scale, S=S, axis=axis,
                           vary=vary),
                   mesh=mesh, in_specs=(sq, sk, sv), out_specs=sq)
    return fn(q, k, v)


def _plain_causal(q, k, v, sm_scale):
    qt, kt, vt = (jnp.swapaxes(t, 1, 2).astype(jnp.float32) for t in (q, k, v))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * sm_scale
    L = logits.shape[-1]
    mask = jnp.tril(jnp.ones((L, L), bool))
    logits = jnp.where(mask, logits, -jnp.inf)
    out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def shard_sequence(x, mesh: Optional[Mesh] = None, axis: str = "sep",
                   seq_dim: int = 1):
    """Place a [B, S, ...] array with S sharded over the sep axis."""
    mesh = mesh if mesh is not None else get_mesh()
    spec = [None] * x.ndim
    spec[seq_dim] = axis
    arr = x.value() if hasattr(x, "value") else x
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def gather_sequence(x, mesh: Optional[Mesh] = None, axis: str = "sep"):
    """Re-replicate a sequence-sharded array (the all-gather at SP exit)."""
    mesh = mesh if mesh is not None else get_mesh()
    arr = x.value() if hasattr(x, "value") else x
    return jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim))))
