"""meta_parallel — hybrid-parallel model wrappers and parallel layers.

Reference analog: python/paddle/distributed/fleet/meta_parallel/.
"""
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from .wrappers import TensorParallel, ShardingParallel  # noqa: F401
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .compiled_pipeline import CompiledPipeline, pipeline_apply  # noqa: F401
from ...random import get_rng_state_tracker  # noqa: F401
