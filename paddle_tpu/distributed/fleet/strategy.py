"""DistributedStrategy.

Reference analog: framework/distributed_strategy.proto:310-360 + its Python wrapper
fleet/base/distributed_strategy.py (the de-facto capability checklist, SURVEY.md §2.4).
Feature booleans select behaviors; *_configs dicts carry knobs. Features whose work is
subsumed by the compiler (fuse_all_reduce_ops, fp16_allreduce, hierarchical allreduce)
are accepted and recorded for parity but are no-ops: XLA fuses/schedules collectives.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # reference proto defaults (distributed_strategy.proto:310-360)
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                            "use_fp16_guard": True}
        self.recompute = False
        # granularity: "full" | "selective" | "dots" (fleet/recompute.py
        # policy layer; selective = Megatron-style, drop only the attention
        # score/softmax region); interval: checkpoint every Nth block.
        # distributed_model() applies these to models exposing
        # enable_recompute (GPT/LLaMA).
        self.recompute_configs = {"checkpoints": [], "enable_offload": False,
                                  "granularity": "full", "interval": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "sharding_degree": 1,
                                 "offload": False, "comm_overlap": True,
                                 # coalesce per-microbatch grad reduce-scatters
                                 # smaller than this into flat fused buckets
                                 # inside the compiled step (None/0 = one
                                 # collective per param; see jit.TrainStep)
                                 "grad_bucket_bytes": None}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        # dp_degree -1 = infer from device count (reference default: dp auto)
        self.hybrid_configs = {
            "dp_degree": -1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.heter_ccl_mode = False
        self.lars = False
        self.lars_configs = {}
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.localsgd = False
        self.a_sync = False
        self.elastic = False
        self.auto = False
        self.semi_auto = False
        self.fuse_all_reduce_ops = True    # no-op: XLA fuses
        self.fuse_grad_size_in_MB = 32     # no-op
        self.fp16_allreduce = False        # no-op: grads keep their dtype
        self.sync_batch_norm = False
        self.find_unused_parameters = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items() if not k.startswith("_")}
        return f"DistributedStrategy({fields})"
