"""Strategy-selected optimizer wrappers: gradient merge, DGC, LocalSGD, LARS.

Reference analogs (fleet/meta_optimizers/*):
- gradient_merge_optimizer.py / GradientMergeConfig: accumulate K micro-steps,
  apply once (k_steps, avg).
- dgc_optimizer.py: Deep Gradient Compression — top-k grad sparsification with
  momentum correction + error feedback (sends ~0.1-1% of grads).
- localsgd_optimizer.py: local updates, periodic parameter averaging.
- lars in optimizer ops (lars_momentum): layer-wise adaptive rate scaling.

TPU-native notes: DP all-reduce itself is compiled into backward (XLA SPMD), so
these wrappers transform GRADIENT/PARAMETER STREAMS, not communication
primitives; DGC's bandwidth saving materializes when grads cross DCN
(multi-host) — the sparsify→error-feedback math is identical either way.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.dispatch import no_grad
from ...core.tensor import Tensor

__all__ = ["GradientMergeOptimizer", "DGCOptimizer", "LocalSGDOptimizer",
           "LarsMomentumOptimizer"]


class _Wrapper:
    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)


class GradientMergeOptimizer(_Wrapper):
    """Accumulate gradients for k_steps, then apply one update (avg option).

    Eager path: the merge buffer below. Compiled path: ``jit.TrainStep``
    recognizes this wrapper (``_gradient_merge`` marker) and compiles the
    accumulation INTO the step executable — K stacked microbatches, one
    ``lax.scan`` forward/backward sweep, one update — so the fleet
    ``gradient_merge`` strategy is a thin adapter onto
    ``TrainStep(accumulate_steps=k_steps, average_grads=avg)``."""

    # adopted by jit.TrainStep while unwrapping the optimizer chain
    _gradient_merge = True

    def __init__(self, optimizer, k_steps: int = 1, avg: bool = True):
        super().__init__(optimizer)
        self.k_steps = max(int(k_steps), 1)
        self.avg = avg
        self._acc = {}
        self._count = 0

    @no_grad()
    def step(self):
        opt = self._inner_opt
        self._count += 1
        for p in opt._parameter_list:
            if p._grad is None:
                continue
            pid = id(p)
            self._acc[pid] = p._grad if pid not in self._acc \
                else self._acc[pid] + p._grad
        if self._count < self.k_steps:
            for p in opt._parameter_list:
                p._grad = None      # grads consumed into the merge buffer
            return
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for p in opt._parameter_list:
            pid = id(p)
            if pid in self._acc:
                p._grad = self._acc[pid] * scale
        self._acc.clear()
        self._count = 0
        opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)


class DGCOptimizer(_Wrapper):
    """Deep Gradient Compression: top-k sparsification + error feedback.

    Each step only the largest `1 - sparsity` fraction of each grad (by
    magnitude) is applied; the remainder accumulates locally and is added back
    next step (momentum-correction form of the reference dgc op)."""

    def __init__(self, optimizer, sparsity: float = 0.999,
                 rampup_begin_step: int = 0):
        super().__init__(optimizer)
        self.sparsity = float(sparsity)
        self.rampup_begin_step = int(rampup_begin_step)
        self._residual = {}
        self._step_num = 0

    @no_grad()
    def step(self):
        opt = self._inner_opt
        self._step_num += 1
        if self._step_num > self.rampup_begin_step:
            for p in opt._parameter_list:
                if p._grad is None:
                    continue
                pid = id(p)
                from ...core.lazy import concrete
                g = concrete(p._grad) + self._residual.get(pid, 0.0)
                flat = jnp.abs(g.reshape(-1))
                k = max(1, int(flat.size * (1.0 - self.sparsity)))
                thresh = jax.lax.top_k(flat, k)[0][-1]
                mask = (jnp.abs(g) >= thresh).astype(g.dtype)
                self._residual[pid] = g * (1.0 - mask)
                p._grad = g * mask
        return opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)


class LocalSGDOptimizer(_Wrapper):
    """Local steps + periodic cross-replica parameter averaging (reference
    localsgd_optimizer). With the single-controller mesh, replicated params
    stay identical and the sync is the identity; on multi-host (per-process
    weights) the sync averages over processes."""

    def __init__(self, optimizer, k_steps: int = 4):
        super().__init__(optimizer)
        self.k_steps = max(int(k_steps), 1)
        self._count = 0

    def step(self):
        r = self._inner_opt.step()
        self._count += 1
        if self._count % self.k_steps == 0:
            self._sync_params()
        return r

    @no_grad()
    def _sync_params(self):
        if jax.process_count() <= 1:
            return  # replicated single-controller params are already equal
        from jax.experimental import multihost_utils
        for p in self._inner_opt._parameter_list:
            orig_sharding = getattr(p.value(), "sharding", None)
            mean = jnp.asarray(
                multihost_utils.process_allgather(p.value()).mean(axis=0))
            if orig_sharding is not None:
                # keep the original placement: a default-device array here
                # would silently recompile every downstream executable
                mean = jax.device_put(mean, orig_sharding)
            p._data = mean
            p._version += 1  # in-place semantics for autograd version guards


class LarsMomentumOptimizer(_Wrapper):
    """LARS: per-layer trust ratio scales the update (reference lars_momentum
    op: local_lr = eta * ||w|| / (||g|| + wd * ||w||))."""

    def __init__(self, optimizer, lars_coeff: float = 0.001,
                 lars_weight_decay: float = 0.0005, epsilon: float = 1e-8,
                 exclude_from_weight_decay=None, **_parity_knobs):
        super().__init__(optimizer)
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay
        self.epsilon = epsilon
        # reference lars_configs: name substrings exempt from decay/scaling
        self.exclude_from_weight_decay = list(exclude_from_weight_decay or [])

    def _excluded(self, p) -> bool:
        name = getattr(p, "name", "") or ""
        return any(pat in name for pat in self.exclude_from_weight_decay)

    @no_grad()
    def step(self):
        opt = self._inner_opt
        for p in opt._parameter_list:
            if p._grad is None or p.ndim < 2 or self._excluded(p):
                continue  # reference skips bias/bn/excluded params
            from ...core.lazy import concrete
            p._grad = concrete(p._grad)  # raw jnp math below
            w_norm = jnp.linalg.norm(p.value().astype(jnp.float32))
            g_norm = jnp.linalg.norm(p._grad.astype(jnp.float32))
            trust = self.lars_coeff * w_norm / (
                g_norm + self.lars_weight_decay * w_norm + self.epsilon)
            trust = jnp.where(w_norm > 0, jnp.where(g_norm > 0, trust, 1.0),
                              1.0)
            p._grad = (p._grad.astype(jnp.float32) * trust
                       + self.lars_weight_decay * trust
                       * p.value().astype(jnp.float32)).astype(p._grad.dtype)
        return opt.step()

    def clear_grad(self, set_to_zero=False):
        return self._inner_opt.clear_grad(set_to_zero=set_to_zero)
