"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred.numpy() if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct.numpy() if isinstance(correct, Tensor) else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            tot = c.shape[0] if c.ndim > 1 else len(c)
            self.total[i] += num
            self.count[i] += tot
            accs.append(float(num) / max(tot, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_cls = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_cls == 1) & (l == 1)).sum())
        self.fp += int(((pred_cls == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_cls = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_cls == 1) & (l == 1)).sum())
        self.fn += int(((pred_cls == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)
        pos_prob = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else p.reshape(-1)
        bins = np.round(pos_prob * self.num_thresholds).astype(int)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            auc += self._stat_neg[i] * (tot_pos + self._stat_pos[i] / 2.0)
            tot_pos += self._stat_pos[i]
            tot_neg += self._stat_neg[i]
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = np.asarray(input.numpy() if isinstance(input, Tensor) else input)
    lbl = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    if lbl.ndim == 2 and lbl.shape[1] == 1:
        lbl = lbl[:, 0]
    topk = np.argsort(-pred, axis=-1)[:, :k]
    acc = float((topk == lbl[:, None]).any(axis=1).mean())
    return Tensor(np.asarray(acc, np.float32))
