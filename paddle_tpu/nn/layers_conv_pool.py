"""Conv + pooling layers (reference: python/paddle/nn/layer/conv.py, pooling.py)."""
from __future__ import annotations

import math

from . import functional as F
from .initializer import KaimingUniform, Uniform
from .layer import Layer

__all__ = [
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
    "AvgPool2D", "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
    "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
    "AdaptiveMaxPool3D",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v) if len(v) == n else tuple(v) * n
    return (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n_spatial,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, n_spatial)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._n_spatial = n_spatial
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            wshape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            wshape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = (in_channels // groups) * math.prod(self._kernel_size)
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=wshape, attr=weight_attr,
            default_initializer=None)
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True,
                default_initializer=Uniform(-bound, bound) if bias_attr is None else None)
        else:
            self.bias = None

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, **kw):
        super().__init__()
        self.output_size = output_size


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)
