"""nn.Layer: the module base class.

Reference: python/paddle/nn/layer/layers.py (Layer with _parameters/_sub_layers/_buffers
dicts, hooks, state_dict, to_static_state). Same surface; storage is eager Tensors whose
arrays live in HBM.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from .initializer.api import _resolve_initializer


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = str(np.dtype(convert_dtype(dtype)))
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # --------------------------------------------------------------- registration

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            if not value.name:
                value.name = f"{self._name_scope}.{name}"
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name: str, tensor: Tensor, persistable: bool = True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        else:
            tensor.persistable = True

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        """Reference: Layer.create_parameter (layers.py) with ParamAttr handling."""
        from .initializer.api import calculate_fan
        dtype = dtype or self._dtype
        init = _resolve_initializer(attr, is_bias, default_initializer)
        arr = init(tuple(int(s) for s in shape), convert_dtype(dtype))
        name = None
        trainable = True
        if attr is not None and not isinstance(attr, (bool, str)):
            name = getattr(attr, "name", None)
            trainable = getattr(attr, "trainable", True)
        p = Parameter(arr, name=name, trainable=trainable)
        lr = getattr(attr, "learning_rate", 1.0) if attr is not None else 1.0
        p.optimize_attr["learning_rate"] = lr
        if attr is not None and getattr(attr, "regularizer", None) is not None:
            p.regularizer = attr.regularizer
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        t = Tensor(np.zeros([0], dtype=np.dtype(convert_dtype(dtype or self._dtype))))
        t.name = name or ""
        return t

    # --------------------------------------------------------------- traversal

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{lp}.{pname}" if lp else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{lp}.{bname}" if lp else bname), b

    def _walk(self, prefix: str, include_sublayers: bool):
        yield "", self, prefix
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{name}" if prefix else name
                for item in sub._walk(sp, True):
                    yield item

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = []
        for _, layer, _ in self._walk("", True):
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        for name, layer, lp in self._walk(prefix, True):
            if layer is self and not include_self:
                continue
            yield lp, layer

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # --------------------------------------------------------------- modes

    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # --------------------------------------------------------------- state dict

    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix, include_sublayers):
            dest[name] = p
        for name, layer, lp in self._walk(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if bname in layer._non_persistable_buffer_names:
                    continue
                dest[f"{lp}.{bname}" if lp else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        missing, unexpected = [], []
        own = self.state_dict()
        for name, tgt in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
                if tuple(arr.shape) != tuple(tgt.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: checkpoint {arr.shape} vs "
                        f"model {tuple(tgt.shape)}")
                tgt.set_value(arr.astype(np.dtype(tgt.dtype)))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # --------------------------------------------------------------- dtype/device

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                p._set_value_inplace(p.value().astype(dt))
            for b in self.buffers():
                if np.issubdtype(np.dtype(b.dtype), np.floating):
                    b._set_value_inplace(b.value().astype(dt))
            self._dtype = str(np.dtype(dt))
        if device is not None:
            import jax
            from ..core.tensor import _parse_place
            from ..core.device import Place
            place = device if isinstance(device, Place) else _parse_place(str(device))
            for t in list(self.parameters()) + list(self.buffers()):
                t._data = jax.device_put(t.value(), place.jax_device)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # --------------------------------------------------------------- hooks / call

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self._sub_layers)
        if idx < 0:
            idx += n
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, (tuple, list)) and len(l) == 2:
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)
        return self


def swap_sublayers(model: "Layer", fn) -> "Layer":
    """Rewrite a Layer tree: fn(layer) returns a replacement or None to
    recurse. The ROOT is offered to fn first — a single-layer model must be
    replaceable too (pass-framework + quantization share this walker)."""
    replaced = fn(model)
    if replaced is not None:
        return replaced
    for name, child in list(model.named_children()):
        new_child = fn(child)
        if new_child is not None:
            setattr(model, name, new_child)
        else:
            swap_sublayers(child, fn)
    return model
