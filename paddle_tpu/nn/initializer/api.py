"""Weight initializers (reference: python/paddle/nn/initializer/)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as rng
from ...core.dtype import convert_dtype

_global_weight_init = None
_global_bias_init = None


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


def _host_sample(sampler, shape, dtype):
    # host-side sampling (rng.host_generator docstring: avoids one XLA compile per
    # parameter shape at model-build time); ONE host→device push, no round-trips
    arr = np.asarray(sampler(rng.host_generator(), shape), np.float32)
    return jax.device_put(arr).astype(dtype) if str(dtype) != "float32" \
        else jax.device_put(arr)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return _host_sample(
            lambda g, s: g.uniform(self.low, self.high, s), shape, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return _host_sample(
            lambda g, s: g.normal(self.mean, self.std, s), shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        def trunc(g, s):
            z = g.normal(0.0, 1.0, s)
            bad = np.abs(z) > 2.0
            while bad.any():
                z[bad] = g.normal(0.0, 1.0, bad.sum())
                bad = np.abs(z) > 2.0
            return z * self.std + self.mean
        return _host_sample(trunc, shape, dtype)


def calculate_fan(shape):
    """fan_in, fan_out. Conventions match the reference for checkpoint parity:
    linear weights are [in, out]; conv kernels are OIHW (out, in/groups, *spatial)."""
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) <= 1:
        return (shape[0], shape[0]) if shape else (1, 1)
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = calculate_fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return _host_sample(lambda g, s: g.uniform(-limit, limit, s), shape, dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = calculate_fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return _host_sample(lambda g, s: g.normal(0.0, std, s), shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = calculate_fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return _host_sample(lambda g, s: g.uniform(-limit, limit, s), shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = calculate_fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return _host_sample(lambda g, s: g.normal(0.0, std, s), shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = np.asarray(self.value)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return jnp.asarray(arr.astype(np.dtype(dtype)))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.nn.initializers.orthogonal(scale=self.gain)(
            rng.split_key(), shape, dtype)


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _resolve_initializer(attr, is_bias, default_initializer):
    """ParamAttr/bool/str → initializer callable."""
    init = None
    if attr is not None and not isinstance(attr, (bool, str)):
        init = getattr(attr, "initializer", None) or getattr(attr, "_initializer", None)
        if callable(attr) and not isinstance(attr, Initializer) and init is None:
            init = attr if isinstance(attr, Initializer) else None
    if isinstance(attr, Initializer):
        init = attr
    if init is None:
        init = default_initializer
    if init is None:
        if is_bias:
            init = _global_bias_init or Constant(0.0)
        else:
            init = _global_weight_init or XavierUniform()
    return init
