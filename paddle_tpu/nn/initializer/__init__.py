from .api import (  # noqa: F401
    Initializer, Constant, Uniform, Normal, TruncatedNormal, XavierNormal,
    XavierUniform, KaimingNormal, KaimingUniform, Assign, Orthogonal,
    calculate_gain, set_global_initializer,
)
