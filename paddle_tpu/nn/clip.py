"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByGlobalNorm/Norm/Value consumed by optimizers)."""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, jnp.ndarray]]):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    @no_grad()
    def __call__(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max)) for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference semantics: scale ALL grads by clip_norm/global_norm when exceeded.
    In hybrid-parallel runs the optimizer wrapper sums the squared norms across
    parallel groups before the sqrt (hybrid_parallel_optimizer.py analog)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    @no_grad()
    def __call__(self, params_grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for _, g in params_grads]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, (g.astype(jnp.float32) * scale).astype(g.dtype))
                for p, g in params_grads]
