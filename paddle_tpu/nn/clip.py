"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByGlobalNorm/Norm/Value consumed by optimizers)."""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, jnp.ndarray]]):
        raise NotImplementedError


def _is_sparse(g):
    from ..core.selected_rows import SelectedRows
    return isinstance(g, SelectedRows)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    @no_grad()
    def __call__(self, params_grads):
        # sparse: duplicate rows sum BEFORE clamping (dense equivalence)
        return [(p, g.merge().map_values(
                    lambda v: jnp.clip(v, self.min, self.max))
                 if _is_sparse(g) else jnp.clip(g, self.min, self.max))
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if _is_sparse(g):
                g = g.merge()   # duplicate rows must sum before the norm
                norm = jnp.sqrt(jnp.sum(jnp.square(g.values)))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                    1.0)
                out.append((p, g.map_values(lambda v: v * scale)))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference semantics: scale ALL grads by clip_norm/global_norm when exceeded.
    In hybrid-parallel runs the optimizer wrapper sums the squared norms across
    parallel groups before the sqrt (hybrid_parallel_optimizer.py analog)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    @no_grad()
    def __call__(self, params_grads):
        # SelectedRows contribute through their merged values (duplicate rows
        # sum before squaring — the dense-equivalent norm)
        merged = [(p, g.merge() if _is_sparse(g) else g)
                  for p, g in params_grads]
        sq = [jnp.sum(jnp.square((g.values if _is_sparse(g) else g)
                                 .astype(jnp.float32)))
              for _, g in merged]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, g.map_values(
                    lambda v: (v.astype(jnp.float32) * scale).astype(v.dtype))
                 if _is_sparse(g)
                 else (g.astype(jnp.float32) * scale).astype(g.dtype))
                for p, g in merged]
