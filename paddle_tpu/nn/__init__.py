"""paddle_tpu.nn — layers and functionals (reference: python/paddle/nn)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import (  # noqa: F401
    Layer, LayerList, Sequential, ParameterList, LayerDict,
)
from .layers_common import *  # noqa: F401,F403
from .layers_conv_pool import *  # noqa: F401,F403
from .layers_norm_act_loss import *  # noqa: F401,F403
from .layers_transformer import *  # noqa: F401,F403
from .layers_rnn import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from ..core.tensor import Parameter  # noqa: F401
from .layers_extended import *  # noqa: F401,F403,E402
