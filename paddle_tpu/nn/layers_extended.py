"""Layer classes closing the reference nn surface: distance/margin losses,
CTC/RNNT, unpooling, SpectralNorm, beam-search decoding.

Reference analogs: python/paddle/nn/layer/{loss,distance,norm}.py and
python/paddle/nn/decode.py (BeamSearchDecoder + dynamic_decode)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import functional as F
from .layer import Layer

__all__ = ["PairwiseDistance", "Softmax2D", "CTCLoss", "RNNTLoss",
           "HSigmoidLoss", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
           "MultiMarginLoss", "TripletMarginWithDistanceLoss", "SpectralNorm",
           "BeamSearchDecoder", "dynamic_decode"]


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (reference Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank, reduction=self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size])
        self.bias = (self.create_parameter([num_classes - 1, 1], is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._a
        return F.max_unpool1d(x, indices, k, s, p, output_size=o)


class MaxUnPool2D(MaxUnPool1D):
    def forward(self, x, indices):
        k, s, p, o = self._a
        return F.max_unpool2d(x, indices, k, s, p, output_size=o)


class MaxUnPool3D(MaxUnPool1D):
    def forward(self, x, indices):
        k, s, p, o = self._a
        return F.max_unpool3d(x, indices, k, s, p, output_size=o)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._a = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, r = self._a
        return F.multi_margin_loss(input, label, p, m, w, r)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._a = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        d, m, s, r = self._a
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   d, m, s, r)


class SpectralNorm(Layer):
    """Spectral normalization of a weight (reference SpectralNorm layer:
    power-iteration estimate of sigma_max, returns weight / sigma)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        import numpy.random as npr
        self.weight_u = self.create_parameter([h])
        self.weight_u.set_value(npr.RandomState(0).randn(h).astype(dtype))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w])
        self.weight_v.set_value(npr.RandomState(1).randn(w).astype(dtype))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        wv = weight.value() if isinstance(weight, Tensor) else \
            jnp.asarray(weight)
        mat = jnp.moveaxis(wv, self.dim, 0).reshape(wv.shape[self.dim], -1)
        u = self.weight_u.value()
        v = self.weight_v.value()
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        self.weight_u.set_value(u)
        self.weight_v.set_value(v)
        return Tensor(wv / sigma)


class BeamSearchDecoder:
    """Greedy/beam decoding over a cell (reference nn.decode.BeamSearchDecoder,
    simplified: scores = log_softmax(output_fn(cell_out)))."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder: BeamSearchDecoder, inits=None, max_step_num=32,
                   **kwargs):
    """Beam search driver (reference dynamic_decode). Returns (ids, scores):
    ids [B, beam, T]."""
    cell = decoder.cell
    beam = decoder.beam_size
    state = inits
    # batch inferred from the initial state pytree's leading dim
    first_leaf = jax.tree_util.tree_leaves(
        state.value() if isinstance(state, Tensor) else state)[0]
    B = int(first_leaf.shape[0])

    tokens = np.full((B, beam), decoder.start_token, np.int64)
    scores = np.zeros((B, beam), np.float64)
    scores[:, 1:] = -1e9          # all beams start from the same root
    states = [state] * beam
    finished = np.zeros((B, beam), bool)
    out_ids = []

    for _ in range(max_step_num):
        cand_scores = []
        cand_states = []
        for b in range(beam):
            inp = Tensor(jnp.asarray(tokens[:, b]))
            if decoder.embedding_fn is not None:
                inp = decoder.embedding_fn(inp)
            out, new_state = cell(inp, states[b])
            logits = decoder.output_fn(out) if decoder.output_fn else out
            logp = jax.nn.log_softmax(logits.value(), axis=-1)
            cand_scores.append(scores[:, b:b + 1]
                               + np.where(finished[:, b:b + 1], 0.0,
                                          np.asarray(logp)))
            cand_states.append(new_state)
        V = cand_scores[0].shape[-1]
        allc = np.concatenate(cand_scores, axis=1)         # [B, beam*V]
        top = np.argsort(-allc, axis=1)[:, :beam]
        scores = np.take_along_axis(allc, top, axis=1)
        src_beam = top // V
        tokens = (top % V).astype(np.int64)
        tokens = np.where(finished[np.arange(B)[:, None], src_beam],
                          decoder.end_token, tokens)
        finished = finished[np.arange(B)[:, None], src_beam] | \
            (tokens == decoder.end_token)
        # per-BATCH state backtrace: each batch element follows its own
        # source beam (a global pick would decode batch>0 with wrong state)
        def pick_states(b):
            return jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(
                    [jnp.asarray(leaves[int(src_beam[i, b])])[i]
                     for i in range(B)]),
                *[(st.value() if isinstance(st, Tensor) else st)
                  for st in cand_states])
        states = [pick_states(b) for b in range(beam)]
        out_ids.append(tokens.copy())
        if finished.all():
            break
    ids = np.stack(out_ids, axis=-1)                       # [B, beam, T]
    return Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(scores))
