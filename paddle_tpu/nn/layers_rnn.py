"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-idiomatic: the whole sequence loop is one registered op whose forward is a
`lax.scan`, so XLA compiles a single fused while-loop and the generic vjp gives BPTT.
Gate order matches the reference (i, f, g, o for LSTM; r, z, n for GRU mirroring
paddle/torch layout) so state dicts transfer.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..ops._helpers import _op
from .initializer import Uniform
from .layer import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    gates = x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "GRU":
        xr, xz, xn = jnp.split(x_t @ w_ih.T + b_ih, 3, axis=-1)
        hr, hz, hn = jnp.split(h @ w_hh.T + b_hh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, c
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    h_new = act(gates)
    return h_new, c


def _rnn_fwd(x, init_h, init_c, *weights, mode="LSTM", num_layers=1,
             bidirectional=False, time_major=False, activation="tanh",
             dropout=0.0):
    """x: [B,T,D] (or [T,B,D] if time_major). weights: per (layer, direction):
    w_ih, w_hh, b_ih, b_hh. init_h/init_c: [num_layers*D, B, H]."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # [T,B,D]
    n_dir = 2 if bidirectional else 1
    out = x
    final_h = []
    final_c = []
    widx = 0
    for layer in range(num_layers):
        dir_outs = []
        for d in range(n_dir):
            w_ih, w_hh, b_ih, b_hh = weights[widx:widx + 4]
            widx += 4
            state_idx = layer * n_dir + d
            h0 = init_h[state_idx]
            c0 = init_c[state_idx]
            seq = out if d == 0 else jnp.flip(out, axis=0)

            def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                h, c = carry
                h2, c2 = _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh,
                                    activation)
                return (h2, c2), h2

            (hT, cT), ys = jax.lax.scan(step, (h0, c0), seq)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outs.append(ys)
            final_h.append(hT)
            final_c.append(cT)
        out = dir_outs[0] if n_dir == 1 else jnp.concatenate(dir_outs, axis=-1)
    fh = jnp.stack(final_h, axis=0)
    fc = jnp.stack(final_c, axis=0)
    if not time_major:
        out = jnp.swapaxes(out, 0, 1)
    return out, fh, fc


register_op("rnn", _rnn_fwd)


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        from ..ops import full
        b = batch_ref.shape[batch_dim_idx]
        hidden = self.hidden_size
        return full([b, hidden], init_value, dtype or "float32")

    @property
    def state_shape(self):
        raise NotImplementedError


class _CellCommon(RNNCellBase):
    def __init__(self, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [n_gates * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [n_gates * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [n_gates * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [n_gates * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)


def _cell_op_fwd(x, h, c, w_ih, w_hh, b_ih, b_hh, mode="LSTM", activation="tanh"):
    h2, c2 = _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh, activation)
    return h2, c2


register_op("rnn_cell", _cell_op_fwd)


class SimpleRNNCell(_CellCommon):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, _ = _op("rnn_cell", inputs, states, states, self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh, mode="RNN", activation=self.activation)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(_CellCommon):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            h0 = self.get_initial_states(inputs)
            c0 = self.get_initial_states(inputs)
        else:
            h0, c0 = states
        h, c = _op("rnn_cell", inputs, h0, c0, self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh, mode="LSTM")
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(_CellCommon):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, _ = _op("rnn_cell", inputs, states, states, self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh, mode="GRU")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a sequence loop (python loop in eager; prefer the fused
    SimpleRNN/LSTM/GRU layers which compile to one lax.scan)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import stack
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        states = initial_states
        outs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            x_t = inputs[:, t] if not self.time_major else inputs[t]
            y, states = self.cell(x_t, states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        out = stack(outs, axis=time_axis)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import concat
        s_fw, s_bw = (None, None) if initial_states is None else initial_states
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirectional = direction in ("bidirect", "bidirectional")
        n_dir = 2 if self.bidirectional else 1
        self.n_dir = n_dir
        n_gates = {"LSTM": 4, "GRU": 3, "RNN": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._flat_weights = []
        for layer in range(num_layers):
            for d in range(n_dir):
                in_sz = input_size if layer == 0 else hidden_size * n_dir
                suffix = f"_reverse" if d == 1 else ""
                w_ih = self.create_parameter([n_gates * hidden_size, in_sz],
                                             weight_ih_attr, default_initializer=init)
                w_hh = self.create_parameter([n_gates * hidden_size, hidden_size],
                                             weight_hh_attr, default_initializer=init)
                b_ih = self.create_parameter([n_gates * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
                b_hh = self.create_parameter([n_gates * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", w_ih)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", w_hh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", b_ih)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", b_hh)
                self._flat_weights += [w_ih, w_hh, b_ih, b_hh]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import zeros
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]
        n_states = self.num_layers * self.n_dir
        if initial_states is None:
            h0 = zeros([n_states, b, self.hidden_size], inputs.dtype)
            c0 = zeros([n_states, b, self.hidden_size], inputs.dtype)
        elif self.mode == "LSTM":
            h0, c0 = initial_states
        else:
            h0 = initial_states
            c0 = zeros([n_states, b, self.hidden_size], inputs.dtype)
        out, fh, fc = _op("rnn", inputs, h0, c0, *self._flat_weights,
                          mode=self.mode, num_layers=self.num_layers,
                          bidirectional=self.bidirectional,
                          time_major=self.time_major, activation=self.activation,
                          dropout=float(self.dropout))
        if self.mode == "LSTM":
            return out, (fh, fc)
        return out, fh


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)
