"""Norm, activation, and loss layers (reference: python/paddle/nn/layer/{norm,activation,loss}.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from . import functional as F
from .initializer import Constant
from .layer import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
    "LocalResponseNorm", "RMSNorm",
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
    "LeakyReLU", "PReLU", "ELU", "SELU", "CELU", "Silu", "Swish", "Mish",
    "Hardswish", "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink",
    "Tanhshrink", "Softsign", "Softplus", "LogSigmoid", "Maxout", "ThresholdedReLU",
    "GLU", "RReLU",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "HingeEmbeddingLoss", "TripletMarginLoss",
    "SoftMarginLoss", "MultiLabelSoftMarginLoss", "PoissonNLLLoss",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False and bias_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.weight = None
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW", **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class _SyncBNOp:
    """Cross-process sync BN as a PyLayer: forward all-reduces the per-channel
    (sum, sum-of-squares, count) so every rank normalizes with the GLOBAL
    batch statistics; backward all-reduces the two per-channel grad sums of
    the standard BN gradient so dx matches the global-batch derivative.

    Reference analog: python/paddle/nn/layer/norm.py:1517 (sync_batch_norm_
    op) and operators/sync_batch_norm_op.cu — same two-collective dataflow
    (one in forward, one in backward), here over the eager collective path
    (device psum fast path with host fallback) instead of NCCL.

    Every rank must call forward/backward in the same order (the usual DP
    contract); grads for weight/bias are LOCAL sums — the DataParallel
    gradient all-reduce aggregates them, matching the reference.
    """

    _fn = None

    @classmethod
    def apply(cls, x, weight, bias, epsilon, channel_axis):
        if cls._fn is None:
            from ..autograd import PyLayer

            class _Fn(PyLayer):
                forward = cls._forward
                backward = cls._backward

            cls._fn = _Fn
        return cls._fn.apply(x, weight, bias,
                             epsilon=epsilon, channel_axis=channel_axis)

    @staticmethod
    def _forward(ctx, x, weight, bias, epsilon, channel_axis):
        import jax.numpy as jnp

        from ..distributed.collective import all_reduce
        xv = x.value()
        c = xv.shape[channel_axis]
        axes = tuple(i for i in range(xv.ndim) if i != channel_axis)
        n_local = 1
        for i, s in enumerate(xv.shape):
            if i != channel_axis:
                n_local *= s
        x32 = xv.astype(jnp.float32)
        packed = jnp.concatenate([
            jnp.sum(x32, axis=axes), jnp.sum(x32 * x32, axis=axes),
            jnp.array([float(n_local)], jnp.float32)])
        packed = all_reduce(Tensor(packed)).value()
        n_g = packed[2 * c]
        mean = packed[:c] / n_g
        var = jnp.maximum(packed[c:2 * c] / n_g - mean * mean, 0.0)
        inv = jnp.reciprocal(jnp.sqrt(var + epsilon))
        shape = [1] * xv.ndim
        shape[channel_axis] = c
        xhat = (x32 - mean.reshape(shape)) * inv.reshape(shape)
        y = xhat
        if weight is not None:
            y = y * weight.value().astype(jnp.float32).reshape(shape) \
                + bias.value().astype(jnp.float32).reshape(shape)
        ctx.save_for_backward(x, weight)
        ctx.bn = (xhat, inv, n_g, channel_axis, shape)
        return (Tensor(y.astype(xv.dtype)), Tensor(mean), Tensor(var),
                Tensor(jnp.asarray(n_g)))

    @staticmethod
    def _backward(ctx, dy, _dmean, _dvar, _dn):
        import jax.numpy as jnp

        from ..distributed.collective import all_reduce
        x, weight = ctx.saved_tensor
        xhat, inv, n_g, channel_axis, shape = ctx.bn
        c = xhat.shape[channel_axis]
        axes = tuple(i for i in range(xhat.ndim) if i != channel_axis)
        dyv = dy.value().astype(jnp.float32)
        dxhat = dyv
        if weight is not None:
            dxhat = dyv * weight.value().astype(jnp.float32).reshape(shape)
        packed = jnp.concatenate([jnp.sum(dxhat, axis=axes),
                                  jnp.sum(dxhat * xhat, axis=axes)])
        packed = all_reduce(Tensor(packed)).value()
        g_sum = packed[:c].reshape(shape)
        g_sum_x = packed[c:].reshape(shape)
        dx = inv.reshape(shape) * (dxhat - g_sum / n_g - xhat * g_sum_x / n_g)
        dx = Tensor(dx.astype(x.dtype))
        if weight is None:
            return dx
        dw = Tensor(jnp.sum(dyv * xhat, axis=axes).astype(weight.dtype))
        db = Tensor(jnp.sum(dyv, axis=axes).astype(weight.dtype))
        return dx, dw, db


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (reference: nn/layer/norm.py:1517 SyncBatchNorm).

    Three regimes:
    - SPMD jit: batch stats computed over the sharded batch ARE the global
      stats (XLA inserts the all-reduce) — plain BN is already sync.
    - Eager, multi-process (launcher DP): forward/backward all-reduce the
      batch statistics across ranks via _SyncBNOp, so normalization and
      running stats use the GLOBAL batch, matching reference semantics.
    - Eager, single process: identical to plain BN.
    """

    def forward(self, x):
        from ..core.dispatch import in_trace
        from ..distributed.collective import _mp_mode
        use_stats = self._use_global_stats
        if use_stats is None:
            use_stats = not self.training
        sync = False
        if self.training and not use_stats and not in_trace():
            try:
                sync = _mp_mode(None)
            except Exception:
                sync = False
        if not sync:
            return super().forward(x)
        channel_axis = (1 if self._data_format.startswith("NC") else
                        x.ndim - 1)
        if x.ndim <= 2:
            channel_axis = x.ndim - 1
        y, bmean, bvar, n_g = _SyncBNOp.apply(
            x, self.weight, self.bias, float(self._epsilon), channel_axis)
        from ..core.dispatch import no_grad
        with no_grad():
            m = float(self._momentum)
            n = float(n_g)
            unbiased = bvar * (n / max(n - 1.0, 1.0))
            new_mean = self._mean * m + bmean * (1 - m)
            new_var = self._variance * m + unbiased * (1 - m)
            self._mean._set_value_inplace(
                new_mean._data.astype(self._mean.dtype))
            self._variance._set_value_inplace(
                new_var._data.astype(self._variance.dtype))
        return y

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                None, None, layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """RMSNorm (beyond the v2.4 reference; required by the LLaMA family)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        from ..ops._helpers import _op
        return _op("rms_norm", x, self.weight, epsilon=float(self._epsilon))


def _rms_norm_fwd(x, w, epsilon=1e-6):
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    out = x.astype(jnp.float32) * jnp.reciprocal(jnp.sqrt(var + epsilon))
    return (out * w.astype(jnp.float32)).astype(x.dtype)


from ..core.dispatch import register_op as _reg  # noqa: E402

_reg("rms_norm", _rms_norm_fwd)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[num_channels], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False and bias_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


# ----------------------------------------------------------------- activations


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, **kw):
            super().__init__()
            merged = dict(defaults)
            merged.update({k: v for k, v in kw.items() if k != "name"})
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", lambda x: F.relu(x))
ReLU6 = _act_layer("ReLU6", lambda x: F.relu6(x))
Sigmoid = _act_layer("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _act_layer("Tanh", lambda x: F.tanh(x))
Silu = _act_layer("Silu", lambda x: F.silu(x))
Swish = _act_layer("Swish", lambda x: F.swish(x))
Mish = _act_layer("Mish", lambda x: F.mish(x))
Hardswish = _act_layer("Hardswish", lambda x: F.hardswish(x))
Softsign = _act_layer("Softsign", lambda x: F.softsign(x))
Tanhshrink = _act_layer("Tanhshrink", lambda x: F.tanhshrink(x))
LogSigmoid = _act_layer("LogSigmoid", lambda x: F.log_sigmoid(x))


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class RReLU(Layer):
    def __init__(self, lower=1 / 8, upper=1 / 3, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


# ----------------------------------------------------------------- losses


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction
        self._soft_label = soft_label
        self._axis = axis
        self._use_softmax = use_softmax
        self._label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self._weight, self._ignore_index,
                               self._reduction, self._soft_label, self._axis,
                               self._use_softmax, self._label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index,
                          self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight, self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction
        self._pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self._weight,
                                                  self._reduction, self._pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin,
                                       self._reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self._margin, self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self._args
        return F.triplet_margin_loss(input, positive, negative, m, p, e, s, r)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, f, e, r = self._args
        return F.poisson_nll_loss(input, label, li, f, e, r)
