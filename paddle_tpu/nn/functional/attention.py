"""Attention functionals.

Reference analogs: `phi/kernels/flash_attn_kernel.h` (dynload'd FlashAttention lib) and
`incubate/nn/memory_efficient_attention.py`. On TPU the fused kernel is a Pallas flash
attention (paddle_tpu.kernels.pallas.flash_attention); the default path is plain XLA,
which already fuses the softmax chain well.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import _op

__all__ = ["scaled_dot_product_attention", "flash_attention"]


def _sdpa_fwd(q, k, v, *rest, causal=False, scale=None, has_mask=False,
              dropout_p=0.0):
    # q,k,v: [B, L, H, D] (paddle flash_attn layout)
    qt = jnp.swapaxes(q, 1, 2)  # [B,H,L,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if has_mask:
        mask = rest[0]
        logits = logits + mask.astype(logits.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B,L,H,D]


register_op("sdpa", _sdpa_fwd)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity: [B, L, H, D] layout."""
    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    return _op("sdpa", *args, causal=bool(is_causal), scale=None,
               has_mask=attn_mask is not None, dropout_p=float(dropout_p))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None, use_pallas=None):
    """paddle.nn.functional.flash_attention parity ([B,L,H,D]).

    On real TPU devices ≥ the pallas kernel's tile minimum, dispatches to the Pallas
    flash-attention kernel; otherwise falls back to the XLA softmax-chain (which XLA
    fuses into a flash-like schedule anyway for moderate L).
    """
    if use_pallas is None:
        use_pallas = _pallas_usable(query)
    if use_pallas:
        from ...kernels.pallas.flash_attention import flash_attention_blhd
        out = flash_attention_blhd(query, key, value, causal=causal)
        if return_softmax:
            return out, None
        return out
    out = _op("sdpa", query, key, value, causal=bool(causal), scale=None,
              has_mask=False, dropout_p=float(dropout))
    if return_softmax:
        return out, None
    return out


def _pallas_usable(q):
    try:
        dev = q.value().devices() if hasattr(q, "value") else set()
        if not any(d.platform in ("tpu",) for d in dev):
            return False
    except Exception:
        return False
    shape = q.shape
    return len(shape) == 4 and shape[1] >= 128 and shape[3] >= 64
