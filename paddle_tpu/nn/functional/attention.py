"""Attention functionals.

Reference analogs: `phi/kernels/flash_attn_kernel.h` (dynload'd FlashAttention lib) and
`incubate/nn/memory_efficient_attention.py`. On TPU the fused kernel is a Pallas flash
attention (paddle_tpu.kernels.pallas.flash_attention); the default path is plain XLA,
which already fuses the softmax chain well.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import random as rng
from ...core.dispatch import register_op
from ...core.remat import ATTN_CONTEXT, tag_array
from ...core.tensor import Tensor
from ...ops._helpers import _op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attention_qkv_packed"]


def _sdpa_fwd(q, k, v, *rest, causal=False, scale=None, has_mask=False,
              has_dropkey=False, dropout_p=0.0):
    # q,k,v: [B, L, H, D] (paddle flash_attn layout); rest = [attn_mask][prng_key]
    if k.shape[2] != q.shape[2]:
        # GQA: expand KV heads (the Pallas path folds them in its index map;
        # the XLA fallback materializes — same public semantics either way)
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.swapaxes(q, 1, 2)  # [B,H,L,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if has_mask:
        mask = rest[0]
        logits = logits + mask.astype(logits.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits, axis=-1)
    if has_dropkey:
        # dropout mask drawn inside the op from the key input — fused by XLA, fresh
        # per execution under to_static (key is threaded program state)
        key = rest[1] if has_mask else rest[0]
        keep = jax.random.bernoulli(jax.random.wrap_key_data(key),
                                    1.0 - dropout_p, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_p)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    # checkpoint name on the CONTEXT only: under the "selective" recompute
    # policy the context survives while every [B,H,S,S] intermediate above
    # (logits, probs, dropout mask) stays unnamed and is rematerialized in
    # backward — the Megatron selective-recompute memory/FLOPs trade
    return tag_array(jnp.swapaxes(out, 1, 2), ATTN_CONTEXT)  # [B,L,H,D]


register_op("sdpa", _sdpa_fwd, nondiff_inputs=(3, 4))


def _flash_attn_pallas_fwd(q, k, v, *rest, causal=False, dropout_rate=0.0):
    from ...kernels.pallas.flash_attention import flash_attention_blhd
    seed = rest[0] if rest else 0
    return tag_array(flash_attention_blhd(q, k, v, causal=causal,
                                          dropout_rate=dropout_rate,
                                          seed=seed), ATTN_CONTEXT)


# Pallas flash attention as a dispatch op: flows through the autograd tape; its
# custom_vjp supplies the gradient under the generic jit(vjp) backward. The
# dropout seed (input 3, when present) is a nondiff program-state input.
register_op("flash_attn_pallas", _flash_attn_pallas_fwd, nondiff_inputs=(3,))


def _flash_attn_packed_fwd(qkv, *rest, num_heads, causal=True,
                           dropout_rate=0.0):
    from ...kernels.pallas.flash_attention import flash_attention_qkv_packed
    from ...kernels.pallas.flash_pair import (flash_pair_packed,
                                              pair_layout_supported)
    seed = rest[0] if rest else 0
    d = qkv.shape[-1] // (3 * num_heads)
    if pair_layout_supported(d, num_heads, qkv.shape[1]):
        # single-tile fast path (head-blocks fill the 128-lane quantum;
        # fused single-pass dqkv backward) — kernels/pallas/flash_pair.py
        return tag_array(flash_pair_packed(qkv, num_heads, causal,
                                           dropout_rate=dropout_rate,
                                           seed=seed), ATTN_CONTEXT)
    return tag_array(flash_attention_qkv_packed(qkv, num_heads, causal=causal,
                                                dropout_rate=dropout_rate,
                                                seed=seed), ATTN_CONTEXT)


register_op("flash_attn_qkv_packed", _flash_attn_packed_fwd,
            nondiff_inputs=(1,))


def _flash_attn_lens_fwd(q, k, v, lens, *rest, causal=False, dropout_rate=0.0):
    from ...kernels.pallas.flash_attention import flash_attention_blhd
    seed = rest[0] if rest else 0
    return tag_array(flash_attention_blhd(q, k, v, causal=causal,
                                          dropout_rate=dropout_rate,
                                          seed=seed, kv_lens=lens),
                     ATTN_CONTEXT)


# encoder padding-mask flash: per-sequence kv lengths as a nondiff input
register_op("flash_attn_pallas_lens", _flash_attn_lens_fwd,
            nondiff_inputs=(3, 4))


def _flash_attn_segs_fwd(q, k, v, sq, sk, *rest, causal=False,
                         dropout_rate=0.0):
    from ...kernels.pallas.flash_attention import flash_attention_blhd
    seed = rest[0] if rest else 0
    return tag_array(flash_attention_blhd(q, k, v, causal=causal,
                                          dropout_rate=dropout_rate,
                                          seed=seed, q_segments=sq,
                                          kv_segments=sk), ATTN_CONTEXT)


# packed-sequence flash: segment ids gate attention (same-segment only)
register_op("flash_attn_pallas_segs", _flash_attn_segs_fwd,
            nondiff_inputs=(3, 4, 5))


def _flash_attn_segs_lens_fwd(q, k, v, lens, sq, sk, *rest, causal=False,
                              dropout_rate=0.0):
    from ...kernels.pallas.flash_attention import flash_attention_blhd
    seed = rest[0] if rest else 0
    return tag_array(flash_attention_blhd(q, k, v, causal=causal,
                                          dropout_rate=dropout_rate,
                                          seed=seed, kv_lens=lens,
                                          q_segments=sq, kv_segments=sk),
                     ATTN_CONTEXT)


# padding lengths AND packed segments together (the kernel masks with both)
register_op("flash_attn_pallas_segs_lens", _flash_attn_segs_lens_fwd,
            nondiff_inputs=(3, 4, 5, 6))


def flash_attention_qkv_packed(qkv, num_heads, dropout=0.0, causal=True,
                               training=True):
    """Flash attention on the fused projection output [B, L, 3*H*D] -> the
    pre-packed [B, L, H*D] context (zero layout copies; head_dim % 128 == 0).
    The hot path for MXU-aligned decoder blocks. Off-TPU (no Mosaic), falls
    back to splitting heads through scaled_dot_product_attention."""
    drop = float(dropout) if training else 0.0
    shape = qkv.shape
    d = shape[-1] // (3 * num_heads)
    from ...kernels.pallas.flash_attention import packed_layout_supported
    from ...kernels.pallas.flash_pair import pair_layout_supported
    if not (flash_path_available(shape[1], d, qkv)
            and (packed_layout_supported(d)
                 or pair_layout_supported(d, num_heads, shape[1]))):
        b, L = shape[0], shape[1]
        unwrap = qkv.value() if hasattr(qkv, "value") else qkv
        q, k, v = (Tensor(unwrap[:, :, i * num_heads * d:(i + 1) * num_heads * d]
                          .reshape(b, L, num_heads, d)) for i in range(3))
        out = scaled_dot_product_attention(q, k, v, dropout_p=drop,
                                           is_causal=causal, training=training)
        return out.reshape([b, L, num_heads * d])
    args = [qkv]
    if drop > 0.0:
        seed = rng.int32_seed()
        args.append(Tensor(seed))
    return _op("flash_attn_qkv_packed", *args, num_heads=int(num_heads),
               causal=bool(causal), dropout_rate=drop)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None,
                                 kv_lens=None, q_segments=None,
                                 kv_segments=None):
    """paddle.nn.functional.scaled_dot_product_attention parity: [B, L, H, D] layout.

    TPU-native extensions: kv_lens ([B] int) — per-sequence key counts
    (encoder padding mask, the structured form of attn_mask=[B,1,1,L]);
    q_segments/kv_segments ([B, L] int) — packed-sequence attention. With
    either of these, or with no attn_mask at all, the call routes to the
    Pallas flash kernel on TPU (reference: phi/kernels/flash_attn_kernel.h
    serves encoder and decoder attention alike); arbitrary additive attn_mask
    takes the XLA softmax chain.

    Attention dropout follows the eager-dropout recipe (functional/common.py): the keep
    mask is drawn host-side from the global RNG chain and passed as a nondiff input, so
    the op stays a pure function of its inputs (cacheable executable)."""
    drop = float(dropout_p) if training else 0.0
    if attn_mask is None and _pallas_usable(query):
        seed_args = []
        if drop > 0.0:
            seed = rng.int32_seed()
            seed_args = [Tensor(seed)]
        if q_segments is not None and kv_lens is not None:
            return _op("flash_attn_pallas_segs_lens", query, key, value,
                       kv_lens, q_segments, kv_segments, *seed_args,
                       causal=bool(is_causal), dropout_rate=drop)
        if q_segments is not None:
            return _op("flash_attn_pallas_segs", query, key, value,
                       q_segments, kv_segments, *seed_args,
                       causal=bool(is_causal), dropout_rate=drop)
        if kv_lens is not None:
            return _op("flash_attn_pallas_lens", query, key, value, kv_lens,
                       *seed_args, causal=bool(is_causal), dropout_rate=drop)
        return _op("flash_attn_pallas", query, key, value, *seed_args,
                   causal=bool(is_causal), dropout_rate=drop)
    if kv_lens is not None or q_segments is not None:
        # XLA fallback (or attn_mask given alongside the structured masks):
        # lower lens/segments to an additive mask and COMBINE with any user
        # mask — dropping either silently would attend padding keys
        structured = _structured_to_additive(query, key, kv_lens, q_segments,
                                             kv_segments)
        if attn_mask is None:
            attn_mask = structured
        else:
            am = attn_mask.value() if hasattr(attn_mask, "value") \
                else jnp.asarray(attn_mask)
            attn_mask = Tensor(structured.value() + am.astype(jnp.float32))
    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    if drop > 0.0:
        args.append(Tensor(jax.random.key_data(rng.split_key())))
    return _op("sdpa", *args, causal=bool(is_causal), scale=None,
               has_mask=attn_mask is not None, has_dropkey=drop > 0.0,
               dropout_p=drop)


def _structured_to_additive(query, key, kv_lens, q_segments, kv_segments):
    """[B] lens / [B, L] segment ids -> additive [B, 1, Lq, Lk] mask."""
    lk = key.shape[1]
    lq = query.shape[1]
    unwrap = lambda t: t.value() if hasattr(t, "value") else jnp.asarray(t)
    valid = None
    if kv_lens is not None:
        cols = jnp.arange(lk)[None, :] < unwrap(kv_lens)[:, None]
        valid = jnp.broadcast_to(cols[:, None, :], (cols.shape[0], lq, lk))
    if q_segments is not None:
        sq = unwrap(q_segments)
        sk = unwrap(kv_segments)
        seg_ok = sq[:, :, None] == sk[:, None, :]
        valid = seg_ok if valid is None else (valid & seg_ok)
    add = jnp.where(valid, 0.0, jnp.float32(-1e30))[:, None, :, :]
    return Tensor(add)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None, use_pallas=None):
    """paddle.nn.functional.flash_attention parity ([B,L,H,D]).

    On real TPU devices ≥ the pallas kernel's tile minimum, dispatches to the Pallas
    flash-attention kernel; otherwise falls back to the XLA softmax-chain (which XLA
    fuses into a flash-like schedule anyway for moderate L).
    """
    drop = float(dropout) if training else 0.0
    if use_pallas is None:
        use_pallas = _pallas_usable(query)
    if use_pallas:
        args = [query, key, value]
        if drop > 0.0:
            # in-kernel counter-based dropout; seed drawn from the global RNG
            # chain so to_static replays give fresh masks (threaded state)
            seed = rng.int32_seed()
            args.append(Tensor(seed))
        out = _op("flash_attn_pallas", *args, causal=bool(causal),
                  dropout_rate=drop)
    else:
        out = scaled_dot_product_attention(query, key, value, dropout_p=drop,
                                           is_causal=bool(causal),
                                           training=training)
    if return_softmax:
        return out, None
    return out


def flash_path_available(seq_len, head_dim, sample=None) -> bool:
    """The single gate for the Pallas flash kernel: tile minimums + TPU placement.

    Shared by every caller (functional API, scanned GPT stack) so shape
    constraints stay in one place. `sample` (Tensor or array) decides by actual
    placement when concrete; tracers fall back to the default backend, which is
    where the compiled program will run."""
    if seq_len < 128 or head_dim < 64:
        return False
    if sample is not None:
        arr = sample.value() if hasattr(sample, "value") else sample
        try:
            return any(d.platform == "tpu" for d in arr.devices())
        except Exception:
            pass
    return jax.default_backend() == "tpu"


def _pallas_usable(q):
    shape = q.shape
    return len(shape) == 4 and flash_path_available(shape[1], shape[3], q)
