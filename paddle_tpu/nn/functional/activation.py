"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import _op, make_unary

__all__ = [
    "relu", "relu6", "relu_", "elu", "selu", "celu", "gelu", "sigmoid", "tanh",
    "silu", "swish", "mish", "hardswish", "hardsigmoid", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "softsign", "softplus", "leaky_relu", "prelu",
    "rrelu", "log_sigmoid", "maxout", "softmax", "log_softmax", "gumbel_softmax",
    "glu", "thresholded_relu",
]

relu = make_unary("relu", jax.nn.relu)
relu6 = make_unary("relu6", jax.nn.relu6)
sigmoid = make_unary("sigmoid", jax.nn.sigmoid)
tanh = make_unary("tanh", jnp.tanh)
silu = make_unary("silu", jax.nn.silu)
mish = make_unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
softsign = make_unary("softsign", jax.nn.soft_sign)
log_sigmoid = make_unary("log_sigmoid", jax.nn.log_sigmoid)
tanhshrink = make_unary("tanhshrink", lambda x: x - jnp.tanh(x))


def relu_(x):
    from ...ops import _rewire_inplace, _snapshot
    out = relu(_snapshot(x))
    return _rewire_inplace(x, out)


def elu(x, alpha=1.0, name=None):
    return _op("elu", x, alpha=float(alpha))


register_op("elu", lambda x, alpha=1.0: jax.nn.elu(x, alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _op("selu", x, scale=float(scale), alpha=float(alpha))


register_op("selu", lambda x, scale=1.0507, alpha=1.6733:
            scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)))


def celu(x, alpha=1.0, name=None):
    return _op("celu", x, alpha=float(alpha))


register_op("celu", lambda x, alpha=1.0: jax.nn.celu(x, alpha))


def gelu(x, approximate=False, name=None):
    return _op("gelu", x, approximate=bool(approximate))


register_op("gelu", lambda x, approximate=False: jax.nn.gelu(x, approximate=approximate))


def swish(x, name=None):
    return silu(x)


def hardswish(x, name=None):
    return _op("hardswish", x)


register_op("hardswish", lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _op("hardsigmoid", x, slope=float(slope), offset=float(offset))


register_op("hardsigmoid", lambda x, slope=1 / 6, offset=0.5:
            jnp.clip(x * slope + offset, 0.0, 1.0))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _op("hardtanh", x, min=float(min), max=float(max))


register_op("hardtanh", lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max))


def hardshrink(x, threshold=0.5, name=None):
    return _op("hardshrink", x, threshold=float(threshold))


register_op("hardshrink", lambda x, threshold=0.5:
            jnp.where(jnp.abs(x) > threshold, x, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _op("softshrink", x, threshold=float(threshold))


register_op("softshrink", lambda x, threshold=0.5:
            jnp.where(x > threshold, x - threshold,
                      jnp.where(x < -threshold, x + threshold, 0.0)))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _op("softplus", x, beta=float(beta), threshold=float(threshold))


register_op("softplus", lambda x, beta=1.0, threshold=20.0:
            jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _op("leaky_relu", x, negative_slope=float(negative_slope))


register_op("leaky_relu", lambda x, negative_slope=0.01:
            jax.nn.leaky_relu(x, negative_slope))


def prelu(x, weight, data_format="NCHW", name=None):
    return _op("prelu", x, weight, data_format=str(data_format))


def _prelu_fwd(x, w, data_format="NCHW"):
    if w.size == 1:
        alpha = w.reshape(())
    else:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape[ch_axis] = w.size
        alpha = w.reshape(shape)
    return jnp.where(x >= 0, x, alpha * x)


register_op("prelu", _prelu_fwd)


def rrelu(x, lower=1 / 8, upper=1 / 3, training=True, name=None):
    if training:
        from ...core import random as rng
        import jax as _jax
        a = _jax.random.uniform(rng.split_key(), tuple(x.shape), jnp.float32,
                                lower, upper)
        from ...core.tensor import Tensor
        return _op("rrelu_t", x, Tensor(a))
    return leaky_relu(x, (lower + upper) / 2)


register_op("rrelu_t", lambda x, a: jnp.where(x >= 0, x, a.astype(x.dtype) * x))


def thresholded_relu(x, threshold=1.0, name=None):
    return _op("thresholded_relu", x, threshold=float(threshold))


register_op("thresholded_relu", lambda x, threshold=1.0:
            jnp.where(x > threshold, x, 0.0))


def maxout(x, groups, axis=1, name=None):
    return _op("maxout", x, groups=int(groups), axis=int(axis))


def _maxout_fwd(x, groups=1, axis=1):
    ax = axis % x.ndim
    c = x.shape[ax]
    new_shape = x.shape[:ax] + (c // groups, groups) + x.shape[ax + 1:]
    return jnp.max(x.reshape(new_shape), axis=ax + 1)


register_op("maxout", _maxout_fwd)


def softmax(x, axis=-1, dtype=None, name=None):
    return _op("softmax", x, axis=int(axis))


register_op("softmax", lambda x, axis=-1: jax.nn.softmax(x, axis=axis))


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _op("log_softmax", x, axis=int(axis))


register_op("log_softmax", lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as rng
    from ...core.tensor import Tensor
    import jax as _jax
    g = _jax.random.gumbel(rng.split_key(), tuple(x.shape), jnp.float32)
    return _op("gumbel_softmax", x, Tensor(g), temperature=float(temperature),
               hard=bool(hard), axis=int(axis))


def _gumbel_softmax_fwd(x, g, temperature=1.0, hard=False, axis=-1):
    y = jax.nn.softmax((x + g.astype(x.dtype)) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False) \
            if hasattr(jnp, "put_along_axis") else \
            jnp.take_along_axis(jnp.eye(y.shape[axis], dtype=y.dtype),
                                idx.squeeze(axis), axis=0)
        y = jax.lax.stop_gradient(onehot - y) + y
    return y


register_op("gumbel_softmax", _gumbel_softmax_fwd)


def glu(x, axis=-1, name=None):
    return _op("glu", x, axis=int(axis))


register_op("glu", lambda x, axis=-1: jax.nn.glu(x, axis=axis))
