"""Normalization functionals (reference: python/paddle/nn/functional/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import no_grad, register_op
from ...ops._helpers import _op, static_int_list

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "normalize", "add_dropout_ln"]


def _bn_fwd(x, mean, var, weight=None, bias=None, epsilon=1e-5, channel_axis=1,
            has_affine=True):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jnp.reciprocal(jnp.sqrt(var.reshape(shape) + epsilon))
    out = (x - mean.reshape(shape)) * inv
    if has_affine:
        out = out * weight.reshape(shape) + bias.reshape(shape)
    return out


register_op("batch_norm_infer", _bn_fwd)


def _bn_train_fwd(x, weight=None, bias=None, epsilon=1e-5, channel_axis=1,
                  has_affine=True):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jnp.reciprocal(jnp.sqrt(var.reshape(shape) + epsilon))
    out = (x - mean.reshape(shape)) * inv
    if has_affine:
        out = out * weight.reshape(shape) + bias.reshape(shape)
    return out, mean, var


register_op("batch_norm_train", _bn_train_fwd)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    channel_axis = 1 if data_format.startswith("NC") or x.ndim <= 2 else x.ndim - 1
    if x.ndim <= 2:
        channel_axis = x.ndim - 1
    has_affine = weight is not None
    if use_global_stats is None:
        use_global_stats = not training
    if not use_global_stats:
        args = [x] + ([weight, bias] if has_affine else [])
        out, batch_mean, batch_var = _op("batch_norm_train", *args,
                                         epsilon=float(epsilon),
                                         channel_axis=int(channel_axis),
                                         has_affine=has_affine)
        if running_mean is not None:
            with no_grad():
                m = float(momentum)
                n = 1
                for i, s in enumerate(x.shape):
                    if i != channel_axis:
                        n *= s
                unbiased = batch_var * (n / max(n - 1, 1))
                # Tensor-level arithmetic (not .value() math): under deferred
                # eager the update records into the lazy graph instead of
                # forcing a flush per BN layer
                new_mean = running_mean * m + batch_mean * (1 - m)
                new_var = running_var * m + unbiased * (1 - m)
                running_mean._set_value_inplace(
                    new_mean._data.astype(running_mean.dtype))
                running_var._set_value_inplace(
                    new_var._data.astype(running_var.dtype))
        return out
    args = [x, running_mean, running_var] + ([weight, bias] if has_affine else [])
    return _op("batch_norm_infer", *args, epsilon=float(epsilon),
               channel_axis=int(channel_axis), has_affine=has_affine)


def _layer_norm_fwd(x, weight=None, bias=None, epsilon=1e-5, begin_axis=1,
                    has_scale=True, has_bias=True):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jnp.reciprocal(jnp.sqrt(var + epsilon))
    shape = x.shape[begin_axis:]
    if has_scale:
        out = out * weight.reshape(shape)
    if has_bias:
        out = out + bias.reshape(shape)
    return out


register_op("layer_norm", _layer_norm_fwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    norm_shape = static_int_list(normalized_shape)
    begin_axis = x.ndim - len(norm_shape)
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return _op("layer_norm", *args, epsilon=float(epsilon), begin_axis=int(begin_axis),
               has_scale=weight is not None, has_bias=bias is not None)


def _instance_norm_fwd(x, weight=None, bias=None, epsilon=1e-5, has_affine=True):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jnp.reciprocal(jnp.sqrt(var + epsilon))
    if has_affine:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape) + bias.reshape(shape)
    return out


register_op("instance_norm", _instance_norm_fwd)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    args = [x] + ([weight, bias] if weight is not None else [])
    return _op("instance_norm", *args, epsilon=float(eps),
               has_affine=weight is not None)


def _group_norm_fwd(x, weight=None, bias=None, epsilon=1e-5, num_groups=1,
                    has_affine=True, channel_axis=1):
    n = x.shape[0]
    c = x.shape[channel_axis]
    if channel_axis != 1:
        x_m = jnp.moveaxis(x, channel_axis, 1)
    else:
        x_m = x
    spatial = x_m.shape[2:]
    g = num_groups
    xg = x_m.reshape((n, g, c // g) + spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jnp.reciprocal(jnp.sqrt(var + epsilon))).reshape(x_m.shape)
    if has_affine:
        shape = [1, c] + [1] * (x_m.ndim - 2)
        out = out * weight.reshape(shape) + bias.reshape(shape)
    if channel_axis != 1:
        out = jnp.moveaxis(out, 1, channel_axis)
    return out


register_op("group_norm", _group_norm_fwd)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    args = [x] + ([weight, bias] if weight is not None else [])
    return _op("group_norm", *args, epsilon=float(epsilon), num_groups=int(num_groups),
               has_affine=weight is not None, channel_axis=channel_axis)


def _lrn_fwd(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    # NCHW: normalize across channel windows
    c = x.shape[1]
    sq = jnp.square(x)
    pad_lo = (size - 1) // 2
    pad_hi = size - 1 - pad_lo
    padded = jnp.pad(sq, [(0, 0), (pad_lo, pad_hi)] + [(0, 0)] * (x.ndim - 2))
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + jnp.take(padded, jnp.arange(i, i + c), axis=1)
    div = jnp.power(k + alpha * acc, beta)
    return x / div


register_op("local_response_norm", _lrn_fwd)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _op("local_response_norm", x, size=int(size), alpha=float(alpha),
               beta=float(beta), k=float(k))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _op("normalize", x, p=float(p), axis=int(axis), epsilon=float(epsilon))


def _normalize_fwd(x, p=2.0, axis=1, epsilon=1e-12):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


register_op("normalize", _normalize_fwd)


# ---------------------------------------------- fused residual add+dropout+LN


def _add_dropout_ln_fwd(x, sub, weight, bias, seed, rate=0.0, eps=1e-12):
    from ...kernels.pallas.fused_residual import fused_add_dropout_ln
    shape = x.shape
    h = shape[-1]
    n = 1
    for s in shape[:-1]:
        n *= s
    seed = jnp.atleast_1d(seed).astype(jnp.int32)
    out = fused_add_dropout_ln(x.reshape(n, h), sub.reshape(n, h),
                               weight, bias, seed, float(rate), float(eps))
    return out.reshape(shape)


register_op("fused_add_dropout_ln", _add_dropout_ln_fwd, nondiff_inputs=(4,))


def add_dropout_ln(x, sub, weight, bias, p=0.0, epsilon=1e-12, training=True):
    """out = LayerNorm(x + dropout(sub)) — the transformer sublayer residual
    epilogue, fused into one Pallas pass on TPU (kernels/pallas/
    fused_residual.py: in-kernel PRNG mask, row-stat-only saves, one-pass
    backward). Reference analog: operators/fused/fused_attention_op.cu /
    fused_feedforward_op.cu epilogues. Falls back to the unfused
    composition off-TPU (identical semantics, shared dropout-mask source
    excepted)."""
    import os

    from ...core import random as _rng
    from ...core.tensor import Tensor as _T
    from ...kernels.pallas.fused_residual import fused_ln_path_available
    rate = float(p) if training else 0.0
    if (fused_ln_path_available(x, rate)
            and not os.environ.get("PADDLE_DISABLE_FUSED_LN")):
        # rate==0 reuses one cached device constant: through the tunnel each
        # fresh tiny host->device array costs ~3 ms (see lazy.scalar_const)
        from ...core.lazy import scalar_const
        seed = _rng.int32_seed() if rate > 0.0 else scalar_const(0)
        return _op("fused_add_dropout_ln", x, sub, weight, bias, _T(seed),
                   rate=rate, eps=float(epsilon))
    from .common import dropout as _dropout
    h = x + _dropout(sub, p=rate, training=rate > 0.0)
    return layer_norm(h, x.shape[-1], weight, bias, epsilon=epsilon)
