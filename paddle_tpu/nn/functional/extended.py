"""Extended functionals closing the reference nn.functional surface:
distance/margin losses, CTC/RNNT (log-space DP as lax.scan), spatial sampling
(affine_grid/grid_sample), unpooling, beam-search utilities.

Reference analogs: python/paddle/nn/functional/{loss,distance,vision,common}.py
over the corresponding phi kernels (e.g. phi/kernels/*ctc*, warpctc vendored
lib — here the DP runs as compiled XLA scans instead of a dlopen'd library).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...ops._helpers import _op

__all__ = [
    "pairwise_distance", "diag_embed", "sequence_mask", "zeropad2d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d", "dice_loss",
    "hsigmoid_loss", "npair_loss", "margin_cross_entropy", "ctc_loss",
    "rnnt_loss", "affine_grid", "grid_sample", "gather_tree",
    "temporal_shift", "sparse_attention", "triplet_margin_with_distance_loss",
    "multi_margin_loss", "elu_", "softmax_", "tanh_",
]


# ------------------------------------------------------------------ distances

def _pairwise_fwd(x, y, *, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y + epsilon
    return jnp.linalg.norm(jnp.abs(d), ord=p, axis=-1, keepdims=keepdim)


register_op("pairwise_distance", _pairwise_fwd)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return _op("pairwise_distance", x, y, p=float(p), epsilon=epsilon,
               keepdim=keepdim)


# ---------------------------------------------------------------- embeddings

register_op("diag_embed", lambda x, *, offset=0, dim1=-2, dim2=-1:
            _diag_embed_impl(x, offset, dim1, dim2))


def _diag_embed_impl(x, offset, dim1, dim2):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = out.at[..., r, c].set(x)
    # move the two new dims into place
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
    order = sorted([(d1, nd - 2), (d2, nd - 1)])
    for pos, src in order:
        perm.insert(pos, src)
    return jnp.transpose(out, perm)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    return _op("diag_embed", input, offset=int(offset), dim1=int(dim1),
               dim2=int(dim2))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    arr = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    m = int(maxlen) if maxlen is not None else int(arr.max())
    from ...core.dtype import convert_dtype
    mask = (jnp.arange(m)[None, :] < arr[..., None]).astype(
        convert_dtype(dtype))
    return Tensor(mask)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, t, b = [int(p) for p in padding]
    pads = ([(0, 0), (0, 0), (t, b), (l, r)] if data_format == "NCHW"
            else [(0, 0), (t, b), (l, r), (0, 0)])
    return _op("zeropad2d_op", x, pads=tuple(map(tuple, pads)))


register_op("zeropad2d_op", lambda x, *, pads: jnp.pad(x, pads))


# ---------------------------------------------------------------- unpooling

def _unpool_fwd(x, indices, *, out_spatial):
    # x, indices: [N, C, *spatial_in]; indices index the FLATTENED output
    n, c = x.shape[:2]
    flat = x.reshape(n, c, -1)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    out_len = int(np.prod(out_spatial))
    out = jnp.zeros((n, c, out_len), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, i, v: o.at[i].set(v)))(out, idx, flat)
    return out.reshape((n, c) + tuple(out_spatial))


register_op("max_unpool", _unpool_fwd, nondiff_inputs=(1,))


def _unpool(x, indices, kernel_size, stride, padding, output_size, ndim):
    ks = (kernel_size,) * ndim if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = tuple(ks) if stride is None else (
        (stride,) * ndim if isinstance(stride, int) else tuple(stride))
    spatial_in = tuple(int(s) for s in x.shape[2:])
    pd = (padding,) * ndim if isinstance(padding, int) else tuple(padding)
    if output_size is None:
        out_spatial = tuple((si - 1) * s - 2 * p + k for si, s, k, p in
                            zip(spatial_in, st, ks, pd))
    else:
        out_spatial = tuple(int(s) for s in output_size[-ndim:])
    return _op("max_unpool", x, indices, out_spatial=out_spatial)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 3)


# -------------------------------------------------------------------- losses

def _dice_fwd(iv, lv, *, epsilon=1e-5):
    num_classes = iv.shape[-1]
    lab1h = jax.nn.one_hot(lv[..., 0].astype(jnp.int32), num_classes,
                           dtype=iv.dtype)
    reduce_dims = tuple(range(1, iv.ndim))
    inter = jnp.sum(iv * lab1h, axis=reduce_dims)
    union = jnp.sum(iv, axis=reduce_dims) + jnp.sum(lab1h, axis=reduce_dims)
    return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))


register_op("dice_loss", _dice_fwd, nondiff_inputs=(1,))


def dice_loss(input, label, epsilon=1e-5, name=None):
    return _op("dice_loss", input, label, epsilon=float(epsilon))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid with the DEFAULT complete binary tree (the
    reference's non-custom-tree mode). Dispatch op: grads flow to input,
    weight and bias."""
    args = [input, label, weight] + ([bias] if bias is not None else [])
    return _op("hsigmoid_loss", *args, num_classes=int(num_classes),
               has_bias=bias is not None)


def _hsigmoid_loss_fwd(iv, lv, wv, *rest, num_classes=2, has_bias=False):
    lv = lv.reshape(-1).astype(jnp.int32)
    bv = rest[0] if has_bias else None
    # complete binary heap: leaves live at [num_classes, 2*num_classes);
    # internal nodes 1..num_classes-1 map to weight rows 0..num_classes-2
    code_len = int(math.ceil(math.log2(max(num_classes, 2))))
    node = lv + num_classes
    losses = []
    for _ in range(code_len):
        parent = node // 2                 # internal node visited at this hop
        bit = (node & 1).astype(iv.dtype)  # which child we descended to
        row = jnp.clip(parent - 1, 0, wv.shape[0] - 1)
        valid = (parent >= 1).astype(iv.dtype)
        logits = jnp.einsum("bh,bh->b", iv, wv[row])
        if bv is not None:
            logits = logits + bv.reshape(-1)[row]
        # sigmoid CE against the branch bit, masked once above the root
        losses.append(valid * (jnp.maximum(logits, 0) - logits * bit
                               + jnp.log1p(jnp.exp(-jnp.abs(logits)))))
        node = parent
    return jnp.sum(jnp.stack(losses), axis=0).mean()


register_op("hsigmoid_loss", _hsigmoid_loss_fwd, nondiff_inputs=(1,))


def _npair_fwd(a, p, lv, *, l2_reg=0.002):
    sim = a @ p.T                                        # [B, B]
    same = (lv.reshape(-1)[:, None] == lv.reshape(-1)[None, :]).astype(a.dtype)
    same = same / jnp.maximum(same.sum(-1, keepdims=True), 1)
    xent = -jnp.sum(same * jax.nn.log_softmax(sim, axis=-1), axis=-1).mean()
    reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / (2 * a.shape[0])
    return xent + reg


register_op("npair_loss", _npair_fwd, nondiff_inputs=(2,))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return _op("npair_loss", anchor, positive, labels, l2_reg=float(l2_reg))


def _margin_ce_fwd(lv, yv, *, margin1=1.0, margin2=0.5, margin3=0.0,
                   scale=64.0, reduction="mean"):
    yv = yv.reshape(-1).astype(jnp.int32)
    cos = jnp.clip(lv, -1.0 + 1e-6, 1.0 - 1e-6)
    theta = jnp.arccos(cos)
    tgt = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(yv, lv.shape[-1], dtype=lv.dtype)
    adj = scale * (onehot * tgt + (1 - onehot) * cos)
    logp = jax.nn.log_softmax(adj, axis=-1)
    per = -jnp.take_along_axis(logp, yv[:, None], axis=-1)[:, 0]
    loss = per.mean() if reduction == "mean" else (
        per.sum() if reduction == "sum" else per)
    return loss, jnp.exp(logp)


register_op("margin_cross_entropy", _margin_ce_fwd, nondiff_inputs=(1,))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference margin_cross_entropy)."""
    loss, probs = _op("margin_cross_entropy", logits, label,
                      margin1=float(margin1), margin2=float(margin2),
                      margin3=float(margin3), scale=float(scale),
                      reduction=reduction)
    if return_softmax:
        return loss, probs
    return loss


def _ctc_fwd(logits, labels, input_lengths, label_lengths, *, blank=0):
    """CTC forward (alpha recursion in log space, lax.scan over time).

    logits: [T, B, V] raw scores (log-softmax applied IN the op so the tape
    differentiates through it); labels: [B, L] padded."""
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    T, B, V = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    labels = labels.astype(jnp.int32)
    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.float32(-1e30)

    emit = jnp.take_along_axis(
        jnp.transpose(log_probs, (1, 0, 2)),          # [B, T, V]
        ext[:, None, :].repeat(T, axis=1), axis=2)    # [B, T, S]

    # allowed skip: ext[s] != ext[s-2]
    skip_ok = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit[:, 0, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, emit[:, 0, 1], neg_inf))

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
        prev2 = jnp.where(skip_ok, prev2, neg_inf)
        merged = jnp.logaddexp(alpha, jnp.logaddexp(prev1, prev2))
        new = merged + emit[:, t, :]
        # positions beyond this sample's input length keep the old alpha
        active = (t < input_lengths)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # final: logaddexp of the last two valid extended positions
    last = 2 * label_lengths.astype(jnp.int32)        # index of final blank
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(a_last, jnp.where(label_lengths > 0, a_prev, -1e30))
    return -ll


register_op("ctc_loss", _ctc_fwd, nondiff_inputs=(1, 2, 3))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (reference warpctc kernel; here a compiled log-space DP)."""
    lp = log_probs if isinstance(log_probs, Tensor) \
        else Tensor(jnp.asarray(log_probs))
    per = _op("ctc_loss", lp, labels, input_lengths, label_lengths,
              blank=int(blank))
    if reduction == "mean":
        ll = jnp.maximum((label_lengths.value()
                          if isinstance(label_lengths, Tensor)
                          else jnp.asarray(label_lengths))
                         .astype(jnp.float32), 1.0)
        return (per / Tensor(ll)).mean()     # Tensor ops: stays on the tape
    if reduction == "sum":
        return per.sum()
    return per


def _rnnt_fwd(raw_logits, labels, input_lengths, label_lengths, *, blank=0):
    """Transducer loss: DP over the (T, U) lattice, scanned over T.

    raw_logits: [B, T, U+1, V]; log-softmax applied IN the op (tape-friendly)."""
    logits = jax.nn.log_softmax(raw_logits, axis=-1)
    B, T, U1, V = logits.shape
    U = U1 - 1
    labels = labels.astype(jnp.int32)
    neg_inf = jnp.float32(-1e30)
    blank_lp = logits[..., blank]                          # [B, T, U+1]
    # label emission scores exist only at u < U: gather on the sliced lattice
    lab_lp = jnp.take_along_axis(
        logits[:, :, :U, :], labels[:, None, :, None].repeat(T, 1),
        axis=3)[..., 0]
    lab_lp = jnp.concatenate(
        [lab_lp, jnp.full((B, T, 1), neg_inf)], axis=2)    # [B, T, U+1]

    def t_step(alpha_t, t):
        # alpha_t: [B, U+1] at time t (before consuming frame t)
        # vertical (label) moves within the same frame: prefix recursion
        def vertical(alpha_row):
            def body(c, u):
                prev = c
                cur = jnp.logaddexp(
                    alpha_row[:, u],
                    jnp.where(u > 0, prev + lab_lp[:, t, u - 1], neg_inf))
                return cur, cur
            init = jnp.full((B,), neg_inf)
            _, cols = jax.lax.scan(body, init, jnp.arange(U1))
            return jnp.transpose(cols)                     # [B, U+1]

        new_row = vertical(alpha_t)
        active = (t < input_lengths)[:, None]
        advanced = new_row + blank_lp[:, t, :]             # consume frame t
        return jnp.where(active, advanced, alpha_t), None

    alpha0 = jnp.full((B, U1), neg_inf).at[:, 0].set(0.0)
    alpha_T, _ = jax.lax.scan(t_step, alpha0, jnp.arange(T))
    final = jnp.take_along_axis(alpha_T,
                                label_lengths.astype(jnp.int32)[:, None],
                                axis=1)[:, 0]
    return -final


register_op("rnnt_loss_op", _rnnt_fwd, nondiff_inputs=(1, 2, 3))


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    lv = input if isinstance(input, Tensor) else Tensor(jnp.asarray(input))
    per = _op("rnnt_loss_op", lv, label, input_lengths, label_lengths,
              blank=int(blank))
    if reduction == "mean":
        return per.mean()
    if reduction == "sum":
        return per.sum()
    return per


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    from ... import ops
    dfn = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = dfn(input, positive)
    d_neg = dfn(input, negative)
    if swap:
        d_neg = ops.minimum(d_neg, dfn(positive, negative))
    per = ops.maximum(d_pos - d_neg + margin, 0.0)
    if reduction == "mean":
        return per.mean()
    if reduction == "sum":
        return per.sum()
    return per


def _multi_margin_fwd(iv, yv, *rest, p=1, margin=1.0, reduction="mean"):
    yv = yv.reshape(-1).astype(jnp.int32)
    gold = jnp.take_along_axis(iv, yv[:, None], axis=1)
    m = jnp.maximum(margin - gold + iv, 0) ** p
    m = m.at[jnp.arange(iv.shape[0]), yv].set(0)
    if rest:
        m = m * rest[0][yv][:, None]
    per = m.sum(-1) / iv.shape[1]
    if reduction == "mean":
        return per.mean()
    if reduction == "sum":
        return per.sum()
    return per


register_op("multi_margin_loss", _multi_margin_fwd, nondiff_inputs=(1,))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])
    return _op("multi_margin_loss", *args, p=int(p), margin=float(margin),
               reduction=reduction)


# --------------------------------------------------------- spatial sampling

def affine_grid(theta, out_shape, align_corners=True, name=None):
    # dispatch op: theta is differentiable in the reference (STN training)
    n, _, h, w = [int(s) for s in out_shape]
    return _op("affine_grid", theta, out_hw=(h, w),
               align_corners=bool(align_corners))


def _affine_grid_fwd(tv, out_hw=(1, 1), align_corners=True):
    h, w = out_hw
    n = tv.shape[0]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)   # [H*W, 3]
    grid = jnp.einsum("nij,pj->npi", tv, base)                 # [N, H*W, 2]
    return grid.reshape(n, h, w, 2)


register_op("affine_grid", _affine_grid_fwd)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    # dispatch op: gradients flow to BOTH x and grid (reference grid_sample
    # has grads for both; a tape bypass here silently froze them)
    return _op("grid_sample", x, grid, mode=str(mode),
               padding_mode=str(padding_mode),
               align_corners=bool(align_corners))


def _grid_sample_fwd(xv, gv, mode="bilinear", padding_mode="zeros",
                     align_corners=True):
    n, c, h, w = xv.shape

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1) * (size - 1) / 2
        return ((coord + 1) * size - 1) / 2

    px = unnormalize(gv[..., 0], w)          # [N, Hg, Wg]
    py = unnormalize(gv[..., 1], h)
    if padding_mode == "reflection":
        # triangular-wave reflection about the [0, size-1] range
        px = (w - 1) - jnp.abs((w - 1) - jnp.abs(px) % (2 * max(w - 1, 1)))
        py = (h - 1) - jnp.abs((h - 1) - jnp.abs(py) % (2 * max(h - 1, 1)))

    def sample_one(img, sx, sy):
        # img [C, H, W]; sx/sy [Hg, Wg]
        x0 = jnp.floor(sx).astype(jnp.int32)
        y0 = jnp.floor(sy).astype(jnp.int32)
        fx = sx - x0
        fy = sy - y0

        def fetch(yy, xx):
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            v = img[:, yc, xc]               # [C, Hg, Wg]
            if padding_mode == "zeros":
                inside = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
                return jnp.where(inside[None], v, 0.0)
            return v                          # border/reflection: clamped tap

        if mode == "nearest":
            return fetch(jnp.round(sy).astype(jnp.int32),
                         jnp.round(sx).astype(jnp.int32))
        return (fetch(y0, x0) * ((1 - fx) * (1 - fy))[None]
                + fetch(y0, x0 + 1) * (fx * (1 - fy))[None]
                + fetch(y0 + 1, x0) * ((1 - fx) * fy)[None]
                + fetch(y0 + 1, x0 + 1) * (fx * fy)[None])

    return jax.vmap(sample_one)(xv, px, py)


register_op("grid_sample", _grid_sample_fwd)


# ------------------------------------------------------------- misc utilities

def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree op): ids/parents
    [T, B, beam] -> full sequences following parent pointers from the end."""
    iv = ids.value() if isinstance(ids, Tensor) else jnp.asarray(ids)
    pv = (parents.value() if isinstance(parents, Tensor)
          else jnp.asarray(parents)).astype(jnp.int32)
    T = iv.shape[0]

    def step(beam_idx, t):
        tok = jnp.take_along_axis(iv[t], beam_idx, axis=-1)
        nxt = jnp.take_along_axis(pv[t], beam_idx, axis=-1)
        return nxt, tok

    init = jnp.broadcast_to(jnp.arange(iv.shape[2], dtype=jnp.int32),
                            iv.shape[1:]).astype(jnp.int32)
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return Tensor(toks[::-1])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    # dispatch op (was a tape bypass: gradients silently froze)
    return _op("temporal_shift", x, seg_num=int(seg_num),
               shift_ratio=float(shift_ratio))


def _temporal_shift_fwd(xv, seg_num=1, shift_ratio=0.25):
    nt, c, h, w = xv.shape
    n = nt // seg_num
    v = xv.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])],
                           axis=1)
    right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                             v[:, :-1, fold:2 * fold]], axis=1)
    rest = v[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


register_op("temporal_shift", _temporal_shift_fwd)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention surface ([B,H,L,D] layout); computed as masked
    dense attention — the reference CUDA kernel's CSR sparsity pattern becomes
    an additive mask (XLA fuses the masked softmax; a Pallas block-sparse
    kernel is the optimization path). Offsets/columns are host metadata."""
    qv = query.value() if isinstance(query, Tensor) else jnp.asarray(query)
    kv = key.value() if isinstance(key, Tensor) else jnp.asarray(key)
    vv = value.value() if isinstance(value, Tensor) else jnp.asarray(value)
    B, H, L, D = qv.shape
    off = np.asarray(sparse_csr_offset.numpy()
                     if isinstance(sparse_csr_offset, Tensor)
                     else sparse_csr_offset).astype(np.int64)
    cols = np.asarray(sparse_csr_columns.numpy()
                      if isinstance(sparse_csr_columns, Tensor)
                      else sparse_csr_columns).astype(np.int64)
    mask_np = np.full((B, H, L, L), -1e9, np.float32)
    for b in range(B):
        for hh in range(H):
            for r in range(L):
                lo, hi = off[b, hh, r], off[b, hh, r + 1]
                mask_np[b, hh, r, cols[b, hh, lo:hi]] = 0.0
    # the dense masked attention runs as a dispatch op so q/k/v get grads
    from .attention import scaled_dot_product_attention
    q4 = query if isinstance(query, Tensor) else Tensor(qv)
    k4 = key if isinstance(key, Tensor) else Tensor(kv)
    v4 = value if isinstance(value, Tensor) else Tensor(vv)
    # sdpa takes [B, L, H, D]
    swap = lambda t: t.transpose([0, 2, 1, 3])
    out = scaled_dot_product_attention(
        swap(q4), swap(k4), swap(v4),
        attn_mask=Tensor(mask_np[:, :, :, :]))
    return swap(out)


# ----------------------------------------------------------- inplace variants

def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    x._set_value_inplace(elu(x, alpha).value())
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    from .activation import softmax
    x._set_value_inplace(softmax(x, axis).value())
    return x


def tanh_(x, name=None):
    from ...ops import tanh
    x._set_value_inplace(tanh(x).value())
    return x
