"""Common functionals: linear, dropout, embedding, interpolate, etc.
(reference: python/paddle/nn/functional/common.py, input.py)"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import random as rng
from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...ops._helpers import _op, static_int_list
from ...ops.manipulation import pad  # re-export paddle.nn.functional.pad

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "pad", "interpolate", "upsample", "bilinear", "cosine_similarity",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "label_smooth",
    "class_center_sample", "unfold", "fold",
]


def _linear_fwd(x, w, *rest, has_bias=False):
    out = jnp.matmul(x, w)
    if has_bias:
        out = out + rest[0]
    return out


register_op("linear", _linear_fwd)


def linear(x, weight, bias=None, name=None):
    args = [x, weight] + ([bias] if bias is not None else [])
    return _op("linear", *args, has_bias=bias is not None)


def _dropout_fwd(x, key, p=0.5, mode="upscale_in_train", mask_shape=None):
    # key is an input (8-byte PRNG key), mask drawn INSIDE the op: XLA fuses mask
    # generation (no [x.shape] host→device mask transfer), and under to_static the
    # key is threaded program state so each execution gets a fresh pattern
    shape = mask_shape if mask_shape is not None else x.shape
    mask = jax.random.bernoulli(jax.random.wrap_key_data(key), 1.0 - p, shape)
    mask = jnp.broadcast_to(mask, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / (1.0 - p), 0.0)
    return jnp.where(mask, x, 0.0)


register_op("dropout", _dropout_fwd, nondiff_inputs=(1,))


def _dropout_pallas_fwd(x, seed, p=0.5, upscale=True):
    from ...kernels.pallas.dropout import dropout_tpu
    return dropout_tpu(x, seed, p, upscale)


def _dropout_pallas_bwd(primals, outs, cts, p=0.5, upscale=True):
    # dx = mask * scale * g — the identical kernel applied to the cotangent
    # (same seed regenerates the same hardware-PRNG mask; nothing saved)
    from ...kernels.pallas.dropout import dropout_tpu
    x, seed = primals
    (g,) = cts
    return (dropout_tpu(g, seed, p, upscale), None)


register_op("dropout_pallas", _dropout_pallas_fwd, bwd=_dropout_pallas_bwd,
            nondiff_inputs=(1,))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    mask_shape = None
    if axis is not None:
        axes = static_int_list(axis)
        mask_shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    if mask_shape is None:
        from ...kernels.pallas.dropout import dropout_path_available
        if dropout_path_available(x):
            # TPU fast path: hardware-PRNG mask generated inside the kernel
            # (kernels/pallas/dropout.py) — ~2 VPU passes vs the ~12 of the
            # XLA threefry chain; bwd regenerates the mask from the seed
            seed = rng.int32_seed()
            return _op("dropout_pallas", x, Tensor(seed), p=float(p),
                       upscale=(mode == "upscale_in_train"))
    key = Tensor(jax.random.key_data(rng.split_key()))
    return _op("dropout", x, key, p=float(p), mode=str(mode),
               mask_shape=mask_shape)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(rng.split_key(), 1.0 - float(p), tuple(x.shape))
    mask = Tensor(keep)
    a = (1.0 - p + p * alpha_p ** 2) ** -0.5
    b = -a * alpha_p * p
    return _op("alpha_dropout", x, mask, alpha_p=float(alpha_p), a=float(a), b=float(b))


register_op("alpha_dropout", lambda x, mask, alpha_p=0.0, a=1.0, b=0.0:
            a * jnp.where(mask, x, alpha_p) + b, nondiff_inputs=(1,))


def _embedding_fwd(w, ids, padding_idx=-1, has_pad=False):
    out = jnp.take(w, ids, axis=0)
    if has_pad:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


register_op("embedding", _embedding_fwd, nondiff_inputs=(1,))


def _embedding_sparse_bwd(primals, outs, cotangents, padding_idx=-1,
                          has_pad=False):
    """Explicit backward producing a SelectedRows weight grad: O(batch·d)
    instead of the dense O(V·d) (reference selected_rows embedding grad,
    phi/kernels/selected_rows/). Duplicate ids stay duplicated — the tape
    concatenates and the optimizer's scatter-add sums them."""
    from ...core.selected_rows import SelectedRows
    w, ids = primals
    ct = cotangents[0]
    rows = ids.reshape(-1).astype(jnp.int32)
    vals = ct.reshape(rows.shape[0], *w.shape[1:])
    if has_pad:
        vals = jnp.where((rows == padding_idx)[:, None], 0.0, vals)
    rows = jnp.clip(rows, 0, w.shape[0] - 1)  # pad ids may be out of range
    return (SelectedRows(rows, vals, w.shape), None)


register_op("embedding_sparse", _embedding_fwd, bwd=_embedding_sparse_bwd,
            nondiff_inputs=(1,))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    from ...core.dispatch import in_trace
    from ...core.tensor import Tensor as _T
    if padding_idx is not None and padding_idx < 0:
        # reference semantics: negative padding_idx counts from the end
        padding_idx = int(weight.shape[0]) + int(padding_idx)
    # sparse grads are an eager feature (reference: selected-rows path);
    # inside a trace the whole-graph vjp keeps grads dense and XLA fuses the
    # scatter. A NON-LEAF weight (tied/scaled embedding) also falls back:
    # its upstream vjp consumes an array cotangent, not SelectedRows.
    weight_is_leaf = not (isinstance(weight, _T)
                          and weight._grad_node is not None)
    op_name = "embedding_sparse" if sparse and weight_is_leaf \
        and not in_trace() else "embedding"
    return _op(op_name, weight, x,
               padding_idx=-1 if padding_idx is None else int(padding_idx),
               has_pad=padding_idx is not None)


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    n_spatial = x.ndim - 2
    if channel_last:
        sp_shape = x.shape[1:-1]
    else:
        sp_shape = x.shape[2:]
    if size is not None:
        out_sizes = static_int_list(size)
    else:
        if isinstance(scale_factor, (int, float)):
            scales = [float(scale_factor)] * n_spatial
        else:
            scales = [float(s) for s in scale_factor]
        out_sizes = tuple(int(s * f) for s, f in zip(sp_shape, scales))
    return _op("interpolate", x, out_sizes=tuple(out_sizes), mode=str(mode),
               align_corners=bool(align_corners), channel_last=channel_last)


def _interpolate_fwd(x, out_sizes=(), mode="nearest", align_corners=False,
                     channel_last=False):
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if channel_last:
        shape = (x.shape[0],) + tuple(out_sizes) + (x.shape[-1],)
    else:
        shape = x.shape[:2] + tuple(out_sizes)
    # jax.image.resize has no align_corners; it matches align_corners=False semantics
    return jax.image.resize(x, shape, method=method)


register_op("interpolate", _interpolate_fwd)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return _op("bilinear", *args, has_bias=bias is not None)


def _bilinear_fwd(x1, x2, w, *rest, has_bias=False):
    # w: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if has_bias:
        out = out + rest[0]
    return out


register_op("bilinear", _bilinear_fwd)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return _op("cosine_similarity", x1, x2, axis=int(axis), eps=float(eps))


def _cos_sim_fwd(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


register_op("cosine_similarity", _cos_sim_fwd)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _op("pixel_shuffle", x, r=int(upscale_factor),
               channel_last=data_format == "NHWC")


def _pixel_shuffle_fwd(x, r=1, channel_last=False):
    if channel_last:
        n, h, w, c = x.shape
        out = x.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return out.reshape(n, c // (r * r), h * r, w * r)


register_op("pixel_shuffle", _pixel_shuffle_fwd)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _op("pixel_unshuffle", x, r=int(downscale_factor),
               channel_last=data_format == "NHWC")


def _pixel_unshuffle_fwd(x, r=1, channel_last=False):
    if channel_last:
        n, h, w, c = x.shape
        out = x.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h // r, w // r, c * r * r)
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // r, r, w // r, r)
    out = out.transpose(0, 1, 3, 5, 2, 4)
    return out.reshape(n, c * r * r, h // r, w // r)


register_op("pixel_unshuffle", _pixel_unshuffle_fwd)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return _op("channel_shuffle", x, groups=int(groups),
               channel_last=data_format == "NHWC")


def _channel_shuffle_fwd(x, groups=1, channel_last=False):
    ax = x.ndim - 1 if channel_last else 1
    c = x.shape[ax]
    moved = jnp.moveaxis(x, ax, 1)
    n = moved.shape[0]
    rest = moved.shape[2:]
    out = moved.reshape((n, groups, c // groups) + rest)
    out = jnp.swapaxes(out, 1, 2).reshape((n, c) + rest)
    return jnp.moveaxis(out, 1, ax)


register_op("channel_shuffle", _channel_shuffle_fwd)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return _op("label_smooth_prior", label, prior_dist, epsilon=float(epsilon))
    return _op("label_smooth", label, epsilon=float(epsilon))


register_op("label_smooth", lambda label, epsilon=0.1:
            (1 - epsilon) * label + epsilon / label.shape[-1])
register_op("label_smooth_prior", lambda label, prior, epsilon=0.1:
            (1 - epsilon) * label + epsilon * prior)


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample lands with the PS/recsys stack")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = static_int_list(kernel_sizes)
    k = k * 2 if len(k) == 1 else k
    s = static_int_list(strides)
    s = s * 2 if len(s) == 1 else s
    p = static_int_list(paddings)
    p = p * 2 if len(p) == 1 else p
    d = static_int_list(dilations)
    d = d * 2 if len(d) == 1 else d
    return _op("unfold", x, k=tuple(k), s=tuple(s), p=tuple(p), d=tuple(d))


def _unfold_fwd(x, k=(3, 3), s=(1, 1), p=(0, 0), d=(1, 1)):
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=((p[0], p[0]), (p[1], p[1])), rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # [N, C*kh*kw, oh, ow] → [N, C*kh*kw, L]
    return patches.reshape(n, patches.shape[1], -1)


register_op("unfold", _unfold_fwd)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    out_hw = static_int_list(output_sizes)
    k = static_int_list(kernel_sizes)
    k = k * 2 if len(k) == 1 else k
    s = static_int_list(strides)
    s = s * 2 if len(s) == 1 else s
    p = static_int_list(paddings)
    p = p * 2 if len(p) == 1 else p
    d = static_int_list(dilations)
    d = d * 2 if len(d) == 1 else d
    return _op("fold", x, out_hw=tuple(out_hw), k=tuple(k), s=tuple(s), p=tuple(p),
               d=tuple(d))


def _fold_fwd(x, out_hw=(1, 1), k=(3, 3), s=(1, 1), p=(0, 0), d=(1, 1)):
    n, ckk, L = x.shape
    c = ckk // (k[0] * k[1])
    oh = (out_hw[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (out_hw[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    cols = x.reshape(n, c, k[0], k[1], oh, ow)
    out = jnp.zeros((n, c, out_hw[0] + 2 * p[0], out_hw[1] + 2 * p[1]), x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            hi = i * d[0]
            wj = j * d[1]
            out = out.at[:, :, hi:hi + oh * s[0]:s[0], wj:wj + ow * s[1]:s[1]].add(
                cols[:, :, i, j])
    return out[:, :, p[0]:out.shape[2] - p[0], p[1]:out.shape[3] - p[1]]


register_op("fold", _fold_fwd)
