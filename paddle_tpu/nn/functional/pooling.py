"""Pooling (reference: python/paddle/nn/functional/pooling.py; phi pool kernels).
All pooling lowers to lax.reduce_window, which XLA fuses well on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import register_op
from ...ops._helpers import _op, static_int_list

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
]


def _norm(v, n):
    t = static_int_list(v)
    return tuple(t * n if len(t) == 1 else t)


def _pool_fwd(x, kernel=(), strides=(), padding=(), mode="max", channel_last=False,
              ceil_mode=False, exclusive=True):
    n_spatial = len(kernel)
    if channel_last:
        window = (1,) + kernel + (1,)
        ws = (1,) + strides + (1,)
        pads = ((0, 0),) + padding + ((0, 0),)
    else:
        window = (1, 1) + kernel
        ws = (1, 1) + strides
        pads = ((0, 0), (0, 0)) + padding
    if mode == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, ws, pads)
    # avg
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, ws, pads)
    if exclusive and any(p != (0, 0) for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, ws, pads)
        return summed / counts
    denom = 1
    for k in kernel:
        denom *= k
    return summed / denom


register_op("pool", _pool_fwd)


def _pool(x, kernel_size, stride, padding, n_spatial, mode, data_format,
          ceil_mode=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    kernel = _norm(kernel_size, n_spatial)
    strides = _norm(stride, n_spatial) if stride is not None else kernel
    if isinstance(padding, str):
        raise NotImplementedError("string padding for pools")
    pad = _norm(padding, n_spatial)
    pads = tuple((p, p) for p in pad)
    if ceil_mode:
        # extend high padding so ceil-division windows fit (matches reference ceil_mode)
        shape = x.shape
        sp_dims = range(1, 1 + n_spatial) if channel_last else range(2, 2 + n_spatial)
        new_pads = []
        for i, d in enumerate(sp_dims):
            size = shape[d] + 2 * pad[i]
            rem = (size - kernel[i]) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem else 0
            new_pads.append((pad[i], pad[i] + extra))
        pads = tuple(new_pads)
    return _op("pool", x, kernel=kernel, strides=strides, padding=pads, mode=mode,
               channel_last=channel_last, exclusive=bool(exclusive))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "max", data_format, ceil_mode)


def _max_pool2d_mask_fwd(x, *, kernel, strides, pads):
    """Max pool + argmax indices into the flattened INPUT spatial plane
    (reference max_pool2d return_mask contract, consumed by max_unpool2d)."""
    import jax.numpy as jnp
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = strides
    ph, pw = pads
    neg = jnp.finfo(jnp.float32).min
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    h2 = (h + 2 * ph - kh) // sh + 1
    w2 = (w + 2 * pw - kw) // sw + 1
    wi = jnp.arange(h2)[:, None] * sh + jnp.arange(kh)[None, :]   # [h2, kh]
    wj = jnp.arange(w2)[:, None] * sw + jnp.arange(kw)[None, :]   # [w2, kw]
    win = xp[:, :, wi[:, None, :, None], wj[None, :, None, :]]    # [n,c,h2,w2,kh,kw]
    flat = win.reshape(n, c, h2, w2, kh * kw)
    arg = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1).astype(x.dtype)
    gi = wi[:, None, :, None] + jnp.zeros((h2, w2, kh, kw), jnp.int32)
    gj = wj[None, :, None, :] + jnp.zeros((h2, w2, kh, kw), jnp.int32)
    gidx = ((gi - ph) * w + (gj - pw)).reshape(h2, w2, kh * kw)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(gidx, (n, c, h2, w2, kh * kw)),
        arg[..., None], axis=-1)[..., 0]
    return out, idx.astype(jnp.int32)


register_op("max_pool2d_mask", _max_pool2d_mask_fwd)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        assert data_format == "NCHW" and not ceil_mode, \
            "return_mask supports NCHW, ceil_mode=False"
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        s = tuple(k) if stride is None else (
            (stride,) * 2 if isinstance(stride, int) else tuple(stride))
        p = (padding,) * 2 if isinstance(padding, int) else tuple(padding)
        return _op("max_pool2d_mask", x, kernel=k, strides=s, pads=p)
    return _pool(x, kernel_size, stride, padding, 2, "max", data_format, ceil_mode)


def _max_pool3d_mask_fwd(x, *, kernel, strides, pads):
    """3-D max pool + argmax into the flattened input volume (reference
    max_pool3d_with_index kernel contract, consumed by max_unpool3d)."""
    import jax.numpy as jnp
    n, c, d, h, w = x.shape
    kd, kh, kw = kernel
    sd, sh, sw = strides
    pd, ph, pw = pads
    neg = jnp.finfo(jnp.float32).min
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)),
                 constant_values=neg)
    d2 = (d + 2 * pd - kd) // sd + 1
    h2 = (h + 2 * ph - kh) // sh + 1
    w2 = (w + 2 * pw - kw) // sw + 1
    wd = jnp.arange(d2)[:, None] * sd + jnp.arange(kd)[None, :]   # [d2, kd]
    wi = jnp.arange(h2)[:, None] * sh + jnp.arange(kh)[None, :]   # [h2, kh]
    wj = jnp.arange(w2)[:, None] * sw + jnp.arange(kw)[None, :]   # [w2, kw]
    win = xp[:, :, wd[:, None, None, :, None, None],
             wi[None, :, None, None, :, None],
             wj[None, None, :, None, None, :]]   # [n,c,d2,h2,w2,kd,kh,kw]
    flat = win.reshape(n, c, d2, h2, w2, kd * kh * kw)
    arg = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1).astype(x.dtype)
    z = jnp.zeros((d2, h2, w2, kd, kh, kw), jnp.int32)
    gd = wd[:, None, None, :, None, None] + z
    gi = wi[None, :, None, None, :, None] + z
    gj = wj[None, None, :, None, None, :] + z
    gidx = (((gd - pd) * h + (gi - ph)) * w + (gj - pw)).reshape(
        d2, h2, w2, kd * kh * kw)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(gidx, (n, c, d2, h2, w2, kd * kh * kw)),
        arg[..., None], axis=-1)[..., 0]
    return out, idx.astype(jnp.int32)


register_op("max_pool3d_mask", _max_pool3d_mask_fwd)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        assert data_format == "NCDHW" and not ceil_mode, \
            "return_mask supports NCDHW, ceil_mode=False"
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        s = tuple(k) if stride is None else (
            (stride,) * 3 if isinstance(stride, int) else tuple(stride))
        p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        return _op("max_pool3d_mask", x, kernel=k, strides=s, pads=p)
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", data_format, ceil_mode,
                 exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format, ceil_mode,
                 exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format, ceil_mode,
                 exclusive)


def _adaptive_pool_fwd(x, out_sizes=(), mode="avg", channel_last=False):
    n_spatial = len(out_sizes)
    sp_dims = list(range(1, 1 + n_spatial)) if channel_last else \
        list(range(x.ndim - n_spatial, x.ndim))
    out = x
    for dim, osize in zip(sp_dims, out_sizes):
        in_size = out.shape[dim]
        if in_size % osize == 0:
            k = in_size // osize
            moved = jnp.moveaxis(out, dim, -1)
            new_shape = moved.shape[:-1] + (osize, k)
            r = moved.reshape(new_shape)
            red = jnp.mean(r, axis=-1) if mode == "avg" else jnp.max(r, axis=-1)
            out = jnp.moveaxis(red, -1, dim)
        else:
            # general adaptive: per-output-window gather (start/end like reference)
            starts = np.floor(np.arange(osize) * in_size / osize).astype(int)
            ends = np.ceil((np.arange(osize) + 1) * in_size / osize).astype(int)
            moved = jnp.moveaxis(out, dim, 0)
            pieces = []
            for s, e in zip(starts, ends):
                seg = moved[s:e]
                pieces.append(jnp.mean(seg, axis=0) if mode == "avg"
                              else jnp.max(seg, axis=0))
            out = jnp.moveaxis(jnp.stack(pieces, axis=0), 0, dim)
    return out


register_op("adaptive_pool", _adaptive_pool_fwd)


def _adaptive(x, output_size, n_spatial, mode, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sizes = static_int_list(output_size)
    if len(out_sizes) == 1:
        out_sizes = out_sizes * n_spatial
    # resolve None entries to input size
    sp_dims = list(range(1, 1 + n_spatial)) if channel_last else \
        list(range(x.ndim - n_spatial, x.ndim))
    resolved = []
    for d, s in zip(sp_dims, out_sizes):
        resolved.append(x.shape[d] if s is None or s < 0 else s)
    return _op("adaptive_pool", x, out_sizes=tuple(resolved), mode=mode,
               channel_last=channel_last)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", "NCDHW")
