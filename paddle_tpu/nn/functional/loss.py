"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import _op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "smooth_l1_loss",
    "nll_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "square_error_cost", "sigmoid_focal_loss",
    "log_loss", "soft_margin_loss", "triplet_margin_loss",
    "multi_label_soft_margin_loss", "poisson_nll_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _ce_fwd(logits, label, soft_label=False, axis=-1, use_softmax=True,
            ignore_index=-100, reduction="mean", has_weight=False, weight=None,
            label_smoothing=0.0):
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
        valid = jnp.ones(loss.shape, jnp.float32)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        n_classes = logits.shape[axis]
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(lbl, n_classes, dtype=logp.dtype, axis=axis)
            smooth = onehot * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(smooth * logp, axis=axis)
        else:
            lbl_safe = jnp.where(lbl == ignore_index, 0, lbl)
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(lbl_safe, axis).astype(jnp.int32), axis=axis)
            loss = jnp.squeeze(loss, axis)
        valid = (lbl != ignore_index).astype(loss.dtype)
        loss = loss * valid
        if has_weight:
            wgt = jnp.take(weight, jnp.where(lbl == ignore_index, 0, lbl).astype(jnp.int32))
            loss = loss * wgt
            valid = valid * wgt
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-9)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    if weight is not None:
        return _op("cross_entropy_w", input, label, weight, soft_label=bool(soft_label),
                   axis=int(axis), use_softmax=bool(use_softmax),
                   ignore_index=int(ignore_index), reduction=str(reduction),
                   label_smoothing=float(label_smoothing))
    return _op("cross_entropy", input, label, soft_label=bool(soft_label),
               axis=int(axis), use_softmax=bool(use_softmax),
               ignore_index=int(ignore_index), reduction=str(reduction),
               label_smoothing=float(label_smoothing))


register_op("cross_entropy",
            lambda logits, label, **kw: _ce_fwd(logits, label, has_weight=False, **kw),
            nondiff_inputs=(1,))
register_op("cross_entropy_w",
            lambda logits, label, weight, **kw: _ce_fwd(logits, label, has_weight=True,
                                                        weight=weight, **kw),
            nondiff_inputs=(1,))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = _op("softmax_ce_noreduce", logits, label, soft_label=bool(soft_label),
               axis=int(axis), ignore_index=int(ignore_index))
    if return_softmax:
        from .activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def _softmax_ce_noreduce(logits, label, soft_label=False, axis=-1, ignore_index=-100):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        return -jnp.sum(label * logp, axis=axis, keepdims=True)
    lbl = label
    squeeze_back = False
    if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis)
        squeeze_back = True
    lbl_safe = jnp.where(lbl == ignore_index, 0, lbl)
    loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl_safe, axis).astype(jnp.int32),
                                axis=axis)
    mask = jnp.expand_dims(lbl != ignore_index, axis)
    loss = jnp.where(mask, loss, 0.0)
    return loss


register_op("softmax_ce_noreduce", _softmax_ce_noreduce, nondiff_inputs=(1,))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])
    return _op("bce", *args, reduction=str(reduction), has_weight=weight is not None)


def _bce_fwd(x, label, *rest, reduction="mean", has_weight=False):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(x, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - x, eps)))
    if has_weight:
        loss = loss * rest[0]
    return _reduce(loss, reduction)


register_op("bce", _bce_fwd, nondiff_inputs=(1,))


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return _op("bce_logits", *args, reduction=str(reduction),
               has_weight=weight is not None, has_pos_weight=pos_weight is not None)


def _bce_logits_fwd(x, label, *rest, reduction="mean", has_weight=False,
                    has_pos_weight=False):
    i = 0
    w = None
    pw = None
    if has_weight:
        w = rest[i]; i += 1
    if has_pos_weight:
        pw = rest[i]
    max_val = jnp.maximum(-x, 0.0)
    if pw is not None:
        log_w = (pw - 1) * label + 1
        loss = (1 - label) * x + log_w * (jnp.log(
            jnp.exp(-max_val) + jnp.exp(-x - max_val)) + max_val)
    else:
        loss = (1 - label) * x + max_val + jnp.log(
            jnp.exp(-max_val) + jnp.exp(-x - max_val))
    if w is not None:
        loss = loss * w
    return _reduce(loss, reduction)


register_op("bce_logits", _bce_logits_fwd, nondiff_inputs=(1,))


def mse_loss(input, label, reduction="mean", name=None):
    return _op("mse_loss", input, label, reduction=str(reduction))


register_op("mse_loss", lambda x, y, reduction="mean":
            _reduce(jnp.square(x - y), reduction))


def square_error_cost(input, label):
    return _op("mse_loss", input, label, reduction="none")


def l1_loss(input, label, reduction="mean", name=None):
    return _op("l1_loss", input, label, reduction=str(reduction))


register_op("l1_loss", lambda x, y, reduction="mean":
            _reduce(jnp.abs(x - y), reduction))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _op("smooth_l1", input, label, reduction=str(reduction), delta=float(delta))


def _smooth_l1_fwd(x, y, reduction="mean", delta=1.0):
    diff = jnp.abs(x - y)
    loss = jnp.where(diff < delta, 0.5 * jnp.square(diff) / delta, diff - 0.5 * delta)
    return _reduce(loss, reduction)


register_op("smooth_l1", _smooth_l1_fwd)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])
    return _op("nll_loss", *args, ignore_index=int(ignore_index),
               reduction=str(reduction), has_weight=weight is not None)


def _nll_fwd(logp, label, *rest, ignore_index=-100, reduction="mean", has_weight=False):
    lbl_safe = jnp.where(label == ignore_index, 0, label).astype(jnp.int32)
    loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl_safe, 1), axis=1).squeeze(1)
    valid = (label != ignore_index).astype(loss.dtype)
    loss = loss * valid
    if has_weight:
        wv = jnp.take(rest[0], lbl_safe)
        loss = loss * wv
        valid = valid * wv
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-9)
    return _reduce(loss, reduction)


register_op("nll_loss", _nll_fwd, nondiff_inputs=(1,))


def kl_div(input, label, reduction="mean", name=None):
    return _op("kl_div", input, label, reduction=str(reduction))


def _kl_div_fwd(logp, target, reduction="mean"):
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - logp)
    if reduction == "batchmean":
        return jnp.sum(loss) / logp.shape[0]
    return _reduce(loss, reduction)


register_op("kl_div", _kl_div_fwd)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return _op("margin_ranking", input, other, label, margin=float(margin),
               reduction=str(reduction))


register_op("margin_ranking", lambda x, y, label, margin=0.0, reduction="mean":
            _reduce(jnp.maximum(-label * (x - y) + margin, 0.0), reduction))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    return _op("cosine_embedding", input1, input2, label, margin=float(margin),
               reduction=str(reduction))


def _cos_emb_fwd(x1, x2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


register_op("cosine_embedding", _cos_emb_fwd, nondiff_inputs=(2,))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _op("hinge_embedding", input, label, margin=float(margin),
               reduction=str(reduction))


register_op("hinge_embedding", lambda x, label, margin=1.0, reduction="mean":
            _reduce(jnp.where(label == 1, x, jnp.maximum(margin - x, 0.0)), reduction),
            nondiff_inputs=(1,))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return _op("focal", *args, alpha=float(alpha), gamma=float(gamma),
               reduction=str(reduction), has_norm=normalizer is not None)


def _focal_fwd(x, label, *rest, alpha=0.25, gamma=2.0, reduction="sum",
               has_norm=False):
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if has_norm:
        loss = loss / rest[0]
    return _reduce(loss, reduction)


register_op("focal", _focal_fwd, nondiff_inputs=(1,))


def log_loss(input, label, epsilon=1e-4, name=None):
    return _op("log_loss", input, label, epsilon=float(epsilon))


register_op("log_loss", lambda p, y, epsilon=1e-4:
            -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon))


def soft_margin_loss(input, label, reduction="mean", name=None):
    return _op("soft_margin", input, label, reduction=str(reduction))


register_op("soft_margin", lambda x, y, reduction="mean":
            _reduce(jnp.log1p(jnp.exp(-y * x)), reduction))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    return _op("triplet", input, positive, negative, margin=float(margin), p=float(p),
               epsilon=float(epsilon), swap=bool(swap), reduction=str(reduction))


def _triplet_fwd(a, pos, neg, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean"):
    def dist(u, v):
        return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
    d_pos = dist(a, pos)
    d_neg = dist(a, neg)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(pos, neg))
    return _reduce(jnp.maximum(d_pos - d_neg + margin, 0.0), reduction)


register_op("triplet", _triplet_fwd)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = [input, label] + ([weight] if weight is not None else [])
    return _op("ml_soft_margin", *args, reduction=str(reduction),
               has_weight=weight is not None)


def _ml_soft_margin_fwd(x, y, *rest, reduction="mean", has_weight=False):
    loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    if has_weight:
        loss = loss * rest[0]
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


register_op("ml_soft_margin", _ml_soft_margin_fwd, nondiff_inputs=(1,))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    return _op("poisson_nll", input, label, log_input=bool(log_input),
               full=bool(full), epsilon=float(epsilon), reduction=str(reduction))


def _poisson_nll_fwd(x, y, log_input=True, full=False, epsilon=1e-8, reduction="mean"):
    if log_input:
        loss = jnp.exp(x) - y * x
    else:
        loss = x - y * jnp.log(x + epsilon)
    if full:
        stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
        loss = loss + jnp.where(y > 1, stirling, 0.0)
    return _reduce(loss, reduction)


register_op("poisson_nll", _poisson_nll_fwd)
