"""Convolutions (reference: python/paddle/nn/functional/conv.py; kernels
phi/kernels/gpu/conv_*). Weight layout is the reference's OIHW for state-dict parity;
lax.conv_general_dilated handles the dimension numbers and XLA lays out for the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import _op, static_int_list

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _norm_tuple(v, n):
    t = static_int_list(v)
    if len(t) == 1:
        t = t * n
    return tuple(t)


def _norm_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    t = static_int_list(padding) if not isinstance(padding, int) else (padding,)
    if len(t) == 1:
        t = t * n
    if len(t) == n:
        return tuple((p, p) for p in t)
    if len(t) == 2 * n:
        return tuple((t[2 * i], t[2 * i + 1]) for i in range(n))
    raise ValueError(f"bad padding {padding}")


def _conv_fwd(x, w, *rest, strides=(), padding="VALID", dilations=(), groups=1,
              n_spatial=2, channel_last=False, has_bias=False):
    spatial = "".join("DHW"[3 - n_spatial:][i] for i in range(n_spatial))
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None,
    )
    if has_bias:
        b = rest[0]
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


register_op("conv", _conv_fwd)


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n_spatial, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    strides = _norm_tuple(stride, n_spatial)
    dilations = _norm_tuple(dilation, n_spatial)
    pad = _norm_padding(padding, n_spatial)
    args = [x, weight]
    if bias is not None:
        args.append(bias)
    return _op("conv", *args, strides=strides, padding=pad, dilations=dilations,
               groups=int(groups), n_spatial=n_spatial, channel_last=channel_last,
               has_bias=bias is not None)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose_fwd(x, w, *rest, strides=(), padding="VALID", output_padding=(),
                        dilations=(), groups=1, n_spatial=2, channel_last=False,
                        has_bias=False):
    spatial = "".join("DHW"[3 - n_spatial:][i] for i in range(n_spatial))
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle conv_transpose weight layout: [in, out/groups, *k]. With
    # transpose_kernel=True lax SWAPS the spec's I/O (it describes the
    # forward-conv kernel whose gradient this is), so the transpose-op's
    # input-channel dim must be labeled "O" here.
    rhs_spec = "OI" + spatial
    if not isinstance(padding, str):
        # paddle semantics: out = (in-1)*s - 2p + k + output_padding
        # ⇒ lax padding = eff_k - 1 - p, with output_padding added on the
        # HIGH side (torch/paddle compute those positions — they are part of
        # the gradient stencil, NOT zero fill)
        ksp = w.shape[2:]
        opad = output_padding or (0,) * len(ksp)
        padding = tuple(
            ((k - 1) * d - lo, (k - 1) * d - hi + op)
            for k, d, (lo, hi), op in zip(ksp, dilations, padding, opad))
        output_padding = ()  # consumed here
    if groups != 1:
        # grouped transpose conv: split and concat along channels
        xs = jnp.split(x, groups, axis=1 if not channel_last else -1)
        ws = jnp.split(w, groups, axis=0)
        outs = [jax.lax.conv_transpose(
            xi, wi, strides=strides, padding=padding, rhs_dilation=dilations,
            dimension_numbers=(lhs_spec, rhs_spec, lhs_spec), transpose_kernel=True)
            for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1 if not channel_last else -1)
    else:
        out = jax.lax.conv_transpose(
            x, w, strides=strides, padding=padding, rhs_dilation=dilations,
            dimension_numbers=(lhs_spec, rhs_spec, lhs_spec), transpose_kernel=True)
    if any(p for p in output_padding):
        pads = [(0, 0)] * out.ndim
        for i, p in enumerate(output_padding):
            d = (i + 2) if not channel_last else (i + 1)
            pads[d] = (0, p)
        out = jnp.pad(out, pads)
    if has_bias:
        b = rest[0]
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


register_op("conv_transpose", _conv_transpose_fwd)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding, dilation,
                       groups, n_spatial, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    strides = _norm_tuple(stride, n_spatial)
    dilations = _norm_tuple(dilation, n_spatial)
    pad = _norm_padding(padding, n_spatial)
    out_pad = _norm_tuple(output_padding, n_spatial) if output_padding else (0,) * n_spatial
    args = [x, weight]
    if bias is not None:
        args.append(bias)
    return _op("conv_transpose", *args, strides=strides, padding=pad,
               output_padding=out_pad, dilations=dilations, groups=int(groups),
               n_spatial=n_spatial, channel_last=channel_last, has_bias=bias is not None)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format, output_size)
