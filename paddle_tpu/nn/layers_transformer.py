"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).
Batch-first [B, L, D] like the reference. Attention lowers to the fused SDPA op
(Pallas flash attention on TPU when shapes allow)."""
from __future__ import annotations

import collections

import numpy as np

from ..core.tensor import Tensor
from ..ops import concat, full, reshape, transpose, triu
from . import functional as F
from .layer import Layer, LayerList
from .layers_common import Dropout, Linear
from .layers_norm_act_loss import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
           "TransformerDecoderLayer", "TransformerDecoder", "Transformer"]


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if str(np.dtype(attn_mask.dtype)) == "bool":
        # True=keep → additive mask
        big_neg = -1e4 if np.dtype(dtype) == np.float16 else -1e9
        return (1.0 - attn_mask.astype(dtype)) * big_neg
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])
    # Preallocated decode cache: fixed [B, max_length, H, Dh] buffers written
    # at `pos` via dynamic_update_slice — shapes never grow, so a compiled
    # decode loop over it never recompiles (the concat Cache grows its length
    # axis every token, minting a new executable per step under jit)
    StaticDecodeCache = collections.namedtuple("StaticDecodeCache",
                                               ["k", "v", "pos"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.need_weights = need_weights
        self.dropout = dropout
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, L, D] → [B, L, H, Dh]
        b, l = x.shape[0], x.shape[1]
        return reshape(x, [b, l, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None, max_length=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        b = key.shape[0]
        from ..ops import zeros
        if type == MultiHeadAttention.StaticDecodeCache:
            if max_length is None:
                raise ValueError(
                    "gen_cache(type=StaticDecodeCache) needs max_length= "
                    "(the preallocated buffer's fixed decode horizon)")
            k = zeros([b, int(max_length), self.num_heads, self.head_dim],
                      key.dtype)
            v = zeros([b, int(max_length), self.num_heads, self.head_dim],
                      key.dtype)
            import jax.numpy as jnp
            return self.StaticDecodeCache(k, v, jnp.int32(0))
        k = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        v = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        if isinstance(cache, self.StaticDecodeCache):
            return self._forward_static_decode(query, key, value, attn_mask,
                                               cache)
        q = self._shape(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)
        mask = _convert_attention_mask(attn_mask, "float32")
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                             dropout_p=self.dropout if self.training else 0.0)
        b, l = out.shape[0], out.shape[1]
        out = reshape(out, [b, l, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None and not isinstance(cache, self.StaticCache):
            return (out, None, cache) if self.need_weights else (out, cache)
        if self.need_weights:
            return out, None
        return out

    def _forward_static_decode(self, query, key, value, attn_mask, cache):
        """Write this chunk's K/V at ``cache.pos`` into the fixed-length
        buffers and attend causally over every cached position <= the
        query's own absolute position. Raw-array math (inference-only): runs
        inside jit with static shapes, so decoding N tokens through it is N
        executions of ONE executable. The cache comes back with pos advanced
        — the namedtuple is the carry, exactly like the concat Cache."""
        if attn_mask is not None:
            raise ValueError(
                "StaticDecodeCache implies causal masking over the cache "
                "cursor; an explicit attn_mask is not supported")
        import math as _math

        import jax
        import jax.numpy as jnp

        q = self._shape(self.q_proj(query))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        k_buf, v_buf = cache.k.value(), cache.v.value()
        pos = cache.pos
        qv, kv, vv = q.value(), k.value(), v.value()
        k_buf = jax.lax.dynamic_update_slice(
            k_buf, kv.astype(k_buf.dtype), (0, pos, 0, 0))
        v_buf = jax.lax.dynamic_update_slice(
            v_buf, vv.astype(v_buf.dtype), (0, pos, 0, 0))
        b, s = qv.shape[0], qv.shape[1]
        m = k_buf.shape[1]
        scores = jnp.einsum("bqnd,bknd->bnqk", qv.astype(jnp.float32),
                            k_buf.astype(jnp.float32)) \
            / _math.sqrt(self.head_dim)
        key_pos = jnp.arange(m)[None, None, None, :]
        q_pos = (pos + jnp.arange(s))[None, None, :, None]
        scores = jnp.where(key_pos <= q_pos, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,bknd->bqnd", probs,
                         v_buf.astype(jnp.float32)).astype(qv.dtype)
        out = self.out_proj(Tensor(ctx.reshape(b, s, self.embed_dim)))
        new_cache = self.StaticDecodeCache(Tensor(k_buf), Tensor(v_buf),
                                           pos + jnp.int32(s))
        if self.need_weights:
            return out, None, new_cache
        return out, new_cache


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src, type=None, max_length=None):
        return self.self_attn.gen_cache(src, type=type, max_length=max_length)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src, type=None, max_length=None):
        return [layer.gen_cache(src, type=type, max_length=max_length)
                for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            # static cache: k/v precomputed over memory, passed via StaticCache
            # (NOT as key/value — those would be re-projected)
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory, type=None, max_length=None):
        # `type`/`max_length` choose the SELF-attention cache form (concat
        # Cache vs preallocated StaticDecodeCache); the cross-attention cache
        # is always the precomputed StaticCache over `memory`
        incremental = self.self_attn.gen_cache(memory, type=type,
                                               max_length=max_length)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([decoder_layer] + [
            copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask,
                                        cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False, type=None, max_length=None):
        return [layer.gen_cache(memory, type=type, max_length=max_length)
                for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        mask = full([length, length], 0.0, "float32")
        upper = triu(full([length, length], float("-inf"), "float32"), diagonal=1)
        return mask + upper
