"""Custom TPU kernels (Pallas).

Reference analog: the hand-written CUDA corpus under /root/reference/paddle/phi/kernels/
gpu and /root/reference/paddle/fluid/operators/fused/. On TPU almost all of that corpus
is XLA's job; Pallas is reserved for the ops where a hand schedule beats the compiler —
flash attention (reference: phi/kernels/flash_attn_kernel.h dynload'd library) being the
canonical one.
"""
