"""Pallas TPU fused residual stream: out = LayerNorm(x + dropout(sub)).

Reference analog: the reference's fused_attention / fused_feedforward ops
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_feedforward_op.cu) exist precisely to fuse the residual-add + dropout
+ LayerNorm epilogue of each transformer sublayer. XLA fuses the elementwise
chain but still materializes the dropout mask and the pre-norm activation in
HBM for the backward; this kernel
  - draws the keep mask from the TPU hardware PRNG inside the kernel
    (never exists in HBM, regenerated in the backward from the same seed),
  - saves only per-ROW statistics (mean, rstd: 2 floats per token) instead
    of the [N, H] pre-norm activation — the backward recomputes h from the
    original inputs, which it has to stream anyway,
  - computes dx, d(sub), and the dweight/dbias partials in ONE pass.

Layout contract: rows = flattened tokens [N, H] with H a 128 multiple; row
tiles chosen to divide N. Stats are f32; IO keeps the input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _keep_mask(seed_ref, i, shape, rate):
    pltpu.prng_seed(seed_ref[0], i)
    bits = pltpu.prng_random_bits(shape)
    bits = jax.lax.bitwise_and(bits, jnp.int32(0x7FFFFFFF))
    return bits >= jnp.int32(int(rate * 2147483648.0))


def _fwd_kernel(seed_ref, x_ref, s_ref, w_ref, b_ref,
                o_ref, stat_ref, *, rate, scale, eps):
    # stat_ref: (2, block) — row 0 mean, row 1 rstd (full first dim so the
    # block satisfies Mosaic's last-two-dims rule)
    i = pl.program_id(0)
    xf = x_ref[:].astype(jnp.float32)
    sf = s_ref[:].astype(jnp.float32)
    if rate > 0.0:
        keep = _keep_mask(seed_ref, i, sf.shape, rate)
        sf = jnp.where(keep, sf * scale, 0.0)
    h = xf + sf
    mean = jnp.mean(h, axis=1, keepdims=True)
    var = jnp.mean(h * h, axis=1, keepdims=True) - mean * mean
    rstd = jax.lax.rsqrt(jnp.maximum(var, 0.0) + eps)
    xhat = (h - mean) * rstd
    out = xhat * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = out.astype(o_ref.dtype)
    stat_ref[0, :] = mean[:, 0]
    stat_ref[1, :] = rstd[:, 0]


def _bwd_kernel(seed_ref, x_ref, s_ref, w_ref, do_ref, stat_ref,
                dx_ref, ds_ref, dp_ref, *, rate, scale, eps):
    # dp_ref: (8, hdim) per tile — row 0 dweight partial, row 1 dbias
    # partial, rows 2-7 zero padding (Mosaic's 8-row sublane quantum)
    i = pl.program_id(0)
    xf = x_ref[:].astype(jnp.float32)
    sf = s_ref[:].astype(jnp.float32)
    keep = None
    if rate > 0.0:
        keep = _keep_mask(seed_ref, i, sf.shape, rate)
        sf = jnp.where(keep, sf * scale, 0.0)
    h = xf + sf
    mean = stat_ref[0, :][:, None]
    rstd = stat_ref[1, :][:, None]
    xhat = (h - mean) * rstd
    dof = do_ref[:].astype(jnp.float32)
    dxhat = dof * w_ref[:].astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dh = rstd * (dxhat - m1 - xhat * m2)
    dx_ref[:] = dh.astype(dx_ref.dtype)
    ds = dh if keep is None else jnp.where(keep, dh * scale, 0.0)
    ds_ref[:] = ds.astype(ds_ref.dtype)
    # per-tile partials; the (tiny) cross-tile sum happens outside
    dp_ref[:] = jnp.zeros_like(dp_ref)
    dp_ref[0, :] = jnp.sum(dof * xhat, axis=0)
    dp_ref[1, :] = jnp.sum(dof, axis=0)


def _row_block(rows, cols, itemsize, target_bytes=1 << 20):
    block = 1
    cap = max(1, target_bytes // max(1, cols * itemsize))
    while block * 2 <= cap and block * 2 <= rows:
        block *= 2
    while rows % block:
        block //= 2
    return max(block, 8 if rows % 8 == 0 else 1)


@functools.partial(jax.jit, static_argnames=("rate", "eps", "interpret"))
def _fused_fwd(x2, s2, w, b, seed, rate, eps, interpret=False):
    n, hdim = x2.shape
    block = _row_block(n, hdim, x2.dtype.itemsize)
    nt = n // block
    scale = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    row = pl.BlockSpec((block, hdim), lambda i, *_: (i, 0))
    vec = pl.BlockSpec((1, hdim), lambda i, *_: (0, 0))
    stat = pl.BlockSpec((2, block), lambda i, *_: (0, i))
    out, stats = pl.pallas_call(
        functools.partial(_fwd_kernel, rate=float(rate), scale=scale,
                          eps=float(eps)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nt,),
            in_specs=[row, row, vec, vec],
            out_specs=[row, stat],
        ),
        out_shape=[jax.ShapeDtypeStruct((n, hdim), x2.dtype),
                   jax.ShapeDtypeStruct((2, n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(seed, x2, s2, w.reshape(1, hdim), b.reshape(1, hdim))
    return out, stats


@functools.partial(jax.jit, static_argnames=("rate", "eps", "interpret"))
def _fused_bwd(x2, s2, w, stats, g2, seed, rate, eps, interpret=False):
    n, hdim = x2.shape
    block = _row_block(n, hdim, x2.dtype.itemsize)
    nt = n // block
    scale = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    row = pl.BlockSpec((block, hdim), lambda i, *_: (i, 0))
    vec = pl.BlockSpec((1, hdim), lambda i, *_: (0, 0))
    stat = pl.BlockSpec((2, block), lambda i, *_: (0, i))
    part = pl.BlockSpec((8, hdim), lambda i, *_: (i, 0))
    dx, ds, dp = pl.pallas_call(
        functools.partial(_bwd_kernel, rate=float(rate), scale=scale,
                          eps=float(eps)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nt,),
            in_specs=[row, row, vec, row, stat],
            out_specs=[row, row, part],
        ),
        out_shape=[jax.ShapeDtypeStruct((n, hdim), x2.dtype),
                   jax.ShapeDtypeStruct((n, hdim), x2.dtype),
                   jax.ShapeDtypeStruct((nt * 8, hdim), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(seed, x2, s2, w.reshape(1, hdim), g2, stats)
    return dx, ds, jnp.sum(dp[0::8], axis=0), jnp.sum(dp[1::8], axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def fused_add_dropout_ln(x2, s2, w, b, seed, rate: float, eps: float,
                         interpret: bool = False):
    """LayerNorm(x2 + dropout(s2, rate)) over rows; x2/s2: [N, H]."""
    out, _ = _fused_fwd(x2, s2, w, b, seed, rate, eps, interpret)
    return out


def _vjp_fwd(x2, s2, w, b, seed, rate, eps, interpret):
    out, stats = _fused_fwd(x2, s2, w, b, seed, rate, eps, interpret)
    return out, (x2, s2, w, stats, seed)


def _vjp_bwd(rate, eps, interpret, res, g):
    x2, s2, w, stats, seed = res
    dx, ds, dw, db = _fused_bwd(x2, s2, w, stats, g, seed, rate, eps,
                                interpret)
    return dx, ds, dw.astype(w.dtype), db.astype(w.dtype), None


fused_add_dropout_ln.defvjp(_vjp_fwd, _vjp_bwd)


def fused_ln_path_available(x, rate: float = 0.0) -> bool:
    """TPU placement + Mosaic tile legality gate. `rate` is accepted for
    call-site symmetry but does not change eligibility: the kernel runs at
    any rate on TPU, and off-TPU the unfused composition is the right
    fallback even at rate==0 (interpret mode is far slower than XLA's fused
    chain). Must not observe the value (deferred eager)."""
    if x.ndim < 2 or x.shape[-1] % 128:
        return False
    try:
        hdim = int(x.shape[-1])
        n = 1
        for s in x.shape[:-1]:
            n *= int(s)
    except Exception:
        # symbolic dims (jax_export dynamic-batch tracing) cannot size the
        # tiles — serve those traces through the unfused composition
        return False
    if n == 0:
        return False
    # the derived row tile must be Mosaic-legal on BOTH layouts it serves:
    # (block, H) row tiles (sublane dim % 8 or == N) and the (2, block)
    # stats lanes (% 128 or == N) — block == N covers both, else the
    # 128-multiple covers both
    import numpy as np
    block = _row_block(n, hdim, np.dtype(x.dtype).itemsize)
    if not (block == n or block % 128 == 0):
        return False
    from .util import tpu_placement
    return tpu_placement(x)
