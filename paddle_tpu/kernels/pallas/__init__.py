from .util import _install_compiler_params_alias  # noqa: F401 (side effect)
from . import flash_attention  # noqa: F401
