"""Pallas TPU flash attention.

Reference analog: phi/kernels/flash_attn_kernel.h — the reference dynloads the CUDA
flash-attention library; here the same memory-hierarchy trick (never materialize the
[L, L] score matrix in HBM, stream K/V blocks through on-chip memory with an online
softmax) is written directly for the TPU: Q blocks live in VMEM per grid step, the K/V
stream is blocked with `lax.fori_loop`, and scores hit the MXU via `jnp.dot` with
fp32 accumulation.

Layout: [B, L, H, D] at the API (paddle flash_attn layout), reshaped to [B*H, L, D]
for the kernel. Backward is recompute-based: the custom_vjp differentiates a
q-chunked, checkpointed XLA implementation, so the bwd holds one [chunk_q, L]
probability block at a time (not the full [L, L] matrix); a hand-written Pallas bwd
kernel is a later optimization.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256   # measured best on v4: 123 TF/s @ (256,256) for L=2048 d=128
DEFAULT_BLOCK_K = 256   # vs 69 TF/s @ (128,128); see bench in git history
_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      sm_scale, causal, block_q, block_k, kv_len, causal_offset):
    # Grid (bh, q_blocks, kv_blocks), kv innermost: each core streams one
    # [block_k, d] K/V tile per step; the online-softmax state (acc, m, l) lives
    # in VMEM scratch and carries across kv steps — only O(block) VMEM regardless
    # of sequence length. kv_len is the true key count (inputs are padded);
    # causal_offset = kv_len - q_len aligns the diagonal for cross-length attention.
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # native-dtype MXU matmul (bf16 in, fp32 accumulate); scale folded in afterwards
    s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    cols = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = cols < kv_len
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = valid & (rows + causal_offset >= cols)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # guard: rows with no valid key yet have m_new == _NEG_INF; exp(s - m_new)
    # would be exp(0) = 1 for every masked column — force those weights to 0
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = m_new

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        # rows with zero valid keys (causal with q_len > kv_len) get 0, matching
        # "no information" rather than a spurious uniform average
        o_ref[:] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _round_up(n, m):
    return ((n + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "block_q",
                                             "block_k", "interpret"))
def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret=False):
    # q,k,v: [BH, Lq, D] / [BH, Lk, D]; any lengths — padded here to block multiples
    bh, q_len, d = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, _round_up(q_len, 8))
    block_k = min(block_k, _round_up(kv_len, 8))
    q_pad = _round_up(q_len, block_q)
    kv_pad = _round_up(kv_len, block_k)
    if q_pad != q_len:
        q = jnp.pad(q, ((0, 0), (0, q_pad - q_len), (0, 0)))
    if kv_pad != kv_len:
        k = jnp.pad(k, ((0, 0), (0, kv_pad - kv_len), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, kv_pad - kv_len), (0, 0)))
    grid = (bh, q_pad // block_q, kv_pad // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
        causal_offset=kv_len - q_len)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out[:, :q_len] if q_pad != q_len else out


def _reference_attention(q, k, v, causal, sm_scale):
    # [BH, L, D]; fp32 math — correctness oracle for tests
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        ql, kl = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if causal:
        # rows with zero valid keys → 0 output (kernel semantics), not uniform avg
        p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bqk,bkd->bqd", p, vf).astype(q.dtype)


_BWD_CHUNK_Q = 512


def _chunked_attention(q, k, v, causal, sm_scale, chunk_q=_BWD_CHUNK_Q):
    """Q-chunked attention whose VJP is memory-light: each chunk's body is
    jax.checkpoint'ed under lax.map, so the backward holds one [chunk_q, Lk]
    probability block at a time instead of the full [Lq, Lk] matrix."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    if lq <= chunk_q:
        return _reference_attention(q, k, v, causal, sm_scale)
    pad = (-lq) % chunk_q
    qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0))) if pad else q
    nc = qp.shape[1] // chunk_q
    qr = jnp.swapaxes(qp.reshape(bh, nc, chunk_q, d), 0, 1)  # [nc, bh, cq, d]
    offsets = jnp.arange(nc) * chunk_q
    offset_diag = lk - lq

    def one_chunk(args):
        qc, off = args
        sf = jnp.einsum("bqd,bkd->bqk", qc.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
        if causal:
            rows = off + jnp.arange(chunk_q)[:, None]
            cols = jnp.arange(lk)[None, :]
            mask = rows + offset_diag >= cols
            sf = jnp.where(mask, sf, _NEG_INF)
        p = jax.nn.softmax(sf, axis=-1)
        if causal:
            p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bqk,bkd->bqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    out = jax.lax.map(jax.checkpoint(one_chunk), (qr, offsets))
    out = jnp.swapaxes(out, 0, 1).reshape(bh, nc * chunk_q, d)
    return out[:, :lq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash(q, k, v, causal, sm_scale, block_q, block_k), (q, k, v)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _chunked_attention(
        q_, k_, v_, causal, sm_scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_blhd(q, k, v, causal=False, sm_scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention on [B, L, H, D] arrays (jax.Array or Tensor-like .value())."""
    unwrap = lambda t: t.value() if hasattr(t, "value") else t
    q, k, v = unwrap(q), unwrap(k), unwrap(v)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    to_bhld = lambda t, L: jnp.swapaxes(t, 1, 2).reshape(b * h, L, d)
    qr = to_bhld(q, lq)
    kr = to_bhld(k, lk)
    vr = to_bhld(v, lk)
    out = _flash(qr, kr, vr, bool(causal), float(sm_scale), block_q, block_k)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)
