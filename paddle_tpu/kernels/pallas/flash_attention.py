"""Pallas TPU flash attention (forward + backward kernels, in-kernel dropout).

Reference analog: phi/kernels/flash_attn_kernel.h — the reference dynloads the CUDA
flash-attention library; here the same memory-hierarchy trick (never materialize the
[L, L] score matrix in HBM, stream K/V blocks through on-chip memory with an online
softmax) is written directly for the TPU: Q blocks live in VMEM per grid step, K/V
tiles stream through as the innermost grid dimension, and scores hit the MXU via
`lax.dot_general` with fp32 accumulation.

Forward saves the per-row log-sum-exp; backward is the standard two-kernel flash
backward (a dQ kernel with K/V innermost and a dK/dV kernel with Q innermost) that
recomputes probabilities from (Q, K, LSE) — O(block) memory at any sequence length.
Causal grids skip fully-masked tiles via `pl.when`, halving the work. Dropout is
generated inside the kernels from a counter-based PRNG seeded per (head, q-tile,
kv-tile) so forward and both backward kernels reproduce the identical mask without
ever materializing it.

VPU economy (the kernels are VPU-bound, not MXU-bound, at D=64): tiles fully
inside the causal band skip the iota/compare/select masking entirely (only
diagonal and padded tiles pay for it), and the softmax scale multiplies the
[block_q, D] query tile (or the dq/dk accumulators at finalize) instead of
every [block_q, block_k] score tile.

Layout: [B, L, H, D] at the API (paddle flash_attn layout), reshaped to [B*H, L, D]
for the kernels (profiled: the reshape costs ~0.06ms/layer against ~0.9ms of
kernel — and Mosaic cannot tile a squeezed head axis directly).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 1024x1024 blocks: device-profiled fastest on v5e (fewer grid steps beats
# finer causal skipping; per-grid-step orchestration overhead dominates at 512)
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def _dropout_mask(seed_ref, bh, qi, kb, shape, rate):
    """Deterministic per-tile keep-mask; identical across fwd/dq/dkv kernels.

    prng_seed accepts at most two words: the head index is hashed into the
    seed word (golden-ratio multiply — no head-count bound), the q/kv tile
    coordinates pack into the second (collision-free to 2^20 q tiles × 2^11
    kv tiles, i.e. beyond any real grid)."""
    head_word = seed_ref[0] ^ (bh * jnp.int32(-1640531527))  # 0x9E3779B9
    tile = (qi * 2048 + kb).astype(jnp.int32)
    pltpu.prng_seed(head_word, tile)
    bits = pltpu.prng_random_bits(shape)  # int32
    # uniform in [0, 2^31): drop iff bits < rate * 2^31 (use non-negative bits)
    bits = jax.lax.bitwise_and(bits, jnp.int32(0x7FFFFFFF))
    threshold = jnp.int32(int(rate * 2147483648.0))
    return bits >= threshold


def _valid_mask(qi, kb, *, causal, block_q, block_k, kv_len, causal_offset,
                len_b=None, sq=None, sk=None):
    """Entry validity for a boundary tile: kv-padding columns off, (for causal)
    entries above the diagonal off, (with per-sequence lengths) columns at or
    beyond this sequence's key count off, and (with segment ids) cross-segment
    entries off. Shared by all three kernels so fwd and bwd probabilities can
    never desynchronize."""
    cols = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = cols < kv_len
    if len_b is not None:
        valid = valid & (cols < len_b)
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = valid & (rows + causal_offset >= cols)
    if sq is not None:
        # packed sequences: only same-segment entries attend (reference
        # encoder semantics: attn over each packed example independently)
        valid = valid & (sq[0, :][:, None] == sk[0, :][None, :])
    return valid


def _tile_liveness(qi, kb, *, causal, block_q, block_k, kv_len, kv_pad,
                   causal_offset, len_b=None, has_segs=False):
    """(live, interior): live = the tile has any valid entry; interior = every
    entry is valid, so masking can be skipped. Padding only exists in the last
    kv tile and only when kv_len isn't a block multiple (static). Per-sequence
    lengths refine both at runtime; segment ids force masking (no cheap
    interior test for arbitrary packings)."""
    if causal:
        live = kb * block_k <= (qi + 1) * block_q - 1 + causal_offset
        below_diag = qi * block_q + causal_offset >= (kb + 1) * block_k - 1
    else:
        live = True
        below_diag = True
    if kv_len < kv_pad:
        unpadded = (kb + 1) * block_k <= kv_len
    else:
        unpadded = True
    interior = below_diag & unpadded
    if len_b is not None:
        live = live & (kb * block_k < len_b)
        interior = interior & ((kb + 1) * block_k <= len_b)
    if has_segs:
        interior = False
    return live, interior


def _grid_ids(grid4d: bool):
    """(bh, qi, kb, n_kv_steps) under either grid layout: 3D (bh, qi, kb) for
    the flat [BH, L, D] kernels, 4D (b, h, qi, kb) for the packed-qkv kernels
    (bh = b*H + h seeds dropout identically either way)."""
    if grid4d:
        bh = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        return bh, pl.program_id(2), pl.program_id(3), pl.num_programs(3)
    return (pl.program_id(0), pl.program_id(1), pl.program_id(2),
            pl.num_programs(2))


def _flash_fwd_kernel(seed_ref, lens_ref, *refs,
                      sm_scale, causal, block_q, block_k, kv_len, kv_pad,
                      causal_offset, dropout_rate, has_lens=False,
                      has_segs=False, n_heads=1, grid4d=False):
    # Grid (bh, q_blocks, kv_blocks), kv innermost: the online-softmax state
    # (acc, m, l) lives in VMEM scratch and carries across kv steps — only
    # O(block) VMEM regardless of sequence length. kv_len is the true key count
    # (inputs are padded); causal_offset = kv_len - q_len aligns the diagonal.
    # lens_ref ([B] int32 scalar-prefetch) gives per-sequence key counts
    # (encoder padding masks); sq/sk segment-id tiles gate packed sequences.
    if has_segs:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref, \
            acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        sq_ref = sk_ref = None
    bh, qi, kb, n_kv = _grid_ids(grid4d)
    b_idx = pl.program_id(0) if grid4d else bh // n_heads
    len_b = lens_ref[b_idx] if has_lens else None

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    live, interior = _tile_liveness(
        qi, kb, causal=causal, block_q=block_q, block_k=block_k,
        kv_len=kv_len, kv_pad=kv_pad, causal_offset=causal_offset,
        len_b=len_b, has_segs=has_segs)

    def body(masked):
        # scale folded into the [block_q, D] query tile, not the score tile
        qs = (q_ref[:].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(qs, k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            valid = _valid_mask(qi, kb, causal=causal, block_q=block_q,
                                block_k=block_k, kv_len=kv_len,
                                causal_offset=causal_offset, len_b=len_b,
                                sq=sq_ref[:] if has_segs else None,
                                sk=sk_ref[:] if has_segs else None)
            s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            # rows with no valid key yet have m_new == _NEG_INF; exp(s - m_new)
            # would be exp(0) = 1 for every masked column — force those to 0
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_mask(seed_ref, bh, qi, kb, (block_q, block_k),
                                 dropout_rate)
            # dropout acts on the normalized matrix; applied to the unnormalized
            # p here, the final acc/l division yields dropout(softmax(s)) @ v
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(live & interior)
    def _interior():
        body(masked=False)

    @pl.when(live & jnp.logical_not(interior))
    def _boundary():
        body(masked=True)

    @pl.when(kb == n_kv - 1)
    def _finalize():
        # rows with zero valid keys (causal with q_len > kv_len) get 0, matching
        # "no information" rather than a spurious uniform average
        o_ref[:] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
        lse_ref[0, :] = (m_ref[:, 0]
                        + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30)))


def _flash_dq_kernel(seed_ref, lens_ref, *refs,
                     sm_scale, causal, block_q, block_k, kv_len, kv_pad,
                     causal_offset, dropout_rate, has_lens=False,
                     has_segs=False, n_heads=1, grid4d=False):
    # Grid (bh, q_blocks, kv_blocks), kv innermost; dq accumulates in VMEM.
    if has_segs:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref, \
            dq_ref, dq_acc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
        sq_ref = sk_ref = None
    bh, qi, kb, n_kv = _grid_ids(grid4d)
    b_idx = pl.program_id(0) if grid4d else bh // n_heads
    len_b = lens_ref[b_idx] if has_lens else None

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live, interior = _tile_liveness(
        qi, kb, causal=causal, block_q=block_q, block_k=block_k,
        kv_len=kv_len, kv_pad=kv_pad, causal_offset=causal_offset,
        len_b=len_b, has_segs=has_segs)

    def body(masked):
        qs = (q_ref[:].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(qs, k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, :][:, None]
        p = jnp.exp(s - lse)
        if masked:
            valid = _valid_mask(qi, kb, causal=causal, block_q=block_q,
                                block_k=block_k, kv_len=kv_len,
                                causal_offset=causal_offset, len_b=len_b,
                                sq=sq_ref[:] if has_segs else None,
                                sk=sk_ref[:] if has_segs else None)
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_mask(seed_ref, bh, qi, kb, (block_q, block_k),
                                 dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta_ref[0, :][:, None])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live & interior)
    def _interior():
        body(masked=False)

    @pl.when(live & jnp.logical_not(interior))
    def _boundary():
        body(masked=True)

    @pl.when(kb == n_kv - 1)
    def _finalize():
        # the softmax scale on dS is a scalar — applied once to the [bq, D]
        # accumulator instead of every [bq, bk] dS tile
        dq_ref[:] = (dq_acc[:] * sm_scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(seed_ref, lens_ref, *refs,
                      sm_scale, causal, block_q, block_k, kv_len, kv_pad,
                      causal_offset, dropout_rate, has_lens=False,
                      has_segs=False, n_heads=1, grid4d=False):
    # Grid (bh, kv_blocks, q_blocks), q innermost; dk/dv accumulate in VMEM.
    # (under grid4d: (b, h, kv_blocks, q_blocks))
    if has_segs:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref, \
            dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            dk_ref, dv_ref, dk_acc, dv_acc = refs
        sq_ref = sk_ref = None
    bh, kb, qi, n_q = _grid_ids(grid4d)
    b_idx = pl.program_id(0) if grid4d else bh // n_heads
    len_b = lens_ref[b_idx] if has_lens else None

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live, interior = _tile_liveness(
        qi, kb, causal=causal, block_q=block_q, block_k=block_k,
        kv_len=kv_len, kv_pad=kv_pad, causal_offset=causal_offset,
        len_b=len_b, has_segs=has_segs)

    def body(masked):
        qs = (q_ref[:].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(qs, k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, :][:, None]
        p = jnp.exp(s - lse)
        if masked:
            valid = _valid_mask(qi, kb, causal=causal, block_q=block_q,
                                block_k=block_k, kv_len=kv_len,
                                causal_offset=causal_offset, len_b=len_b,
                                sq=sq_ref[:] if has_segs else None,
                                sk=sk_ref[:] if has_segs else None)
            p = jnp.where(valid, p, 0.0)
        keep_scale = None
        if dropout_rate > 0.0:
            keep = _dropout_mask(seed_ref, bh, qi, kb, (block_q, block_k),
                                 dropout_rate)
            keep_scale = jnp.where(keep, 1.0 / (1.0 - dropout_rate), 0.0)
        # dV = dropped(P)^T @ dO
        p_for_dv = p * keep_scale if keep_scale is not None else p
        dv_acc[:] += jax.lax.dot_general(
            p_for_dv.astype(do_ref.dtype), do_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep_scale is not None:
            dp = dp * keep_scale
        ds = p * (dp - delta_ref[0, :][:, None])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live & interior)
    def _interior():
        body(masked=False)

    @pl.when(live & jnp.logical_not(interior))
    def _boundary():
        body(masked=True)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[:] = (dk_acc[:] * sm_scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(seed_ref, lens_ref, *refs,
                            sm_scale, causal, block_q, block_k, kv_len, kv_pad,
                            causal_offset, dropout_rate, has_lens=False,
                            has_segs=False, n_heads=1):
    # Single-tile backward: when the whole sequence fits one (block_q, block_k)
    # tile pair (the common encoder/decoder training shape: L <= 1024), dq, dk
    # and dv come out of ONE kernel that computes s/p/ds once — the two-kernel
    # flash backward recomputes the score matrix and its exp twice. Grid (bh,).
    if has_segs:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref, \
            dq_ref, dk_ref, dv_ref = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            dq_ref, dk_ref, dv_ref = refs
        sq_ref = sk_ref = None
    bh = pl.program_id(0)
    b_idx = bh // n_heads
    len_b = lens_ref[b_idx] if has_lens else None

    qs = (q_ref[:].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
    s = jax.lax.dot_general(qs, k_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    lse = lse_ref[0, :][:, None]
    p = jnp.exp(s - lse)
    needs_mask = causal or has_segs or has_lens or kv_len < kv_pad
    if needs_mask:
        valid = _valid_mask(0, 0, causal=causal, block_q=block_q,
                            block_k=block_k, kv_len=kv_len,
                            causal_offset=causal_offset, len_b=len_b,
                            sq=sq_ref[:] if has_segs else None,
                            sk=sk_ref[:] if has_segs else None)
        p = jnp.where(valid, p, 0.0)
    keep_scale = None
    if dropout_rate > 0.0:
        zero = jnp.int32(0)  # qi=kb=0: the single tile (ids must be traced)
        keep = _dropout_mask(seed_ref, bh, zero, zero, (block_q, block_k),
                             dropout_rate)
        keep_scale = jnp.where(keep, 1.0 / (1.0 - dropout_rate), 0.0)
    p_for_dv = p * keep_scale if keep_scale is not None else p
    dv_ref[:] = jax.lax.dot_general(
        p_for_dv.astype(do_ref.dtype), do_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if keep_scale is not None:
        dp = dp * keep_scale
    ds = p * (dp - delta_ref[0, :][:, None])
    dsc = ds.astype(q_ref.dtype)
    dq_ref[:] = (jax.lax.dot_general(
        dsc, k_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale).astype(dq_ref.dtype)
    dk_ref[:] = (jax.lax.dot_general(
        dsc, q_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale).astype(dk_ref.dtype)


def _round_up(n, m):
    return ((n + m - 1) // m) * m


def _norm_blocks(block_q, block_k, q_len, kv_len):
    """Clamp blocks to the (padded) lengths and round to the TPU lane quantum:
    the LSE/delta tiles are laid out (1, block) so block sizes must be
    128-multiples for Mosaic lowering."""
    block_q = _round_up(min(block_q, _round_up(q_len, 128)), 128)
    block_k = _round_up(min(block_k, _round_up(kv_len, 128)), 128)
    return block_q, block_k


def _pad_len(x, L, axis=1):
    pad = L - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _kv_map(n_heads: int, kv_heads: int):
    """Flat (b*h) q index -> flat (b*h_kv) K/V index for grouped-query
    attention: `group` consecutive q heads read the same KV head. Identity
    when MHA (kv_heads == n_heads)."""
    if kv_heads == n_heads:
        return lambda b: b
    group = n_heads // kv_heads
    return lambda b: (b // n_heads) * kv_heads + (b % n_heads) // group


def _seg_pads(seg_q, seg_k, q_pad, kv_pad):
    """Pad segment-id arrays ([B, L] int32) to the padded tile lengths with -1
    (pad-pad matches are already masked by the static kv_len / lens tests) and
    reshape to [B, 1, L] so Mosaic lane-tiles them."""
    sq = _pad_len(seg_q[:, None, :].astype(jnp.int32), q_pad, axis=2)
    sk = _pad_len(seg_k[:, None, :].astype(jnp.int32), kv_pad, axis=2)
    return sq, sk


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "block_q",
                                             "block_k", "dropout_rate",
                                             "interpret", "n_heads",
                                             "kv_heads"))
def _flash_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
               dropout_rate=0.0, interpret=False, n_heads=1, kv_heads=1,
               lens=None, seg_q=None, seg_k=None):
    # q: [B*H, Lq, D]; k,v: [B*Hkv, Lk, D] (GQA when Hkv < H; the index map
    # folds q heads onto their KV head — repeated KV never materializes).
    # lens: [B] int32 per-sequence key counts (encoder padding); seg_q/seg_k:
    # [B, L] int32 packed-sequence ids (same-segment attention only).
    bh, q_len, d = q.shape
    kv_len = k.shape[1]
    kvm = _kv_map(n_heads, kv_heads)
    bq = lambda b: b // n_heads  # flat (b*h) -> batch row for lens/segs
    block_q, block_k = _norm_blocks(block_q, block_k, q_len, kv_len)
    q_pad = _round_up(q_len, block_q)
    kv_pad = _round_up(kv_len, block_k)
    q = _pad_len(q, q_pad)
    k = _pad_len(k, kv_pad)
    v = _pad_len(v, kv_pad)
    has_lens = lens is not None
    has_segs = seg_q is not None
    if not has_lens:
        lens = jnp.zeros((1,), jnp.int32)  # placeholder prefetch (unused)
    grid = (bh, q_pad // block_q, kv_pad // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
        causal_offset=kv_len - q_len, dropout_rate=dropout_rate,
        has_lens=has_lens, has_segs=has_segs, n_heads=n_heads)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, i, j, *_: (kvm(b), j, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, i, j, *_: (kvm(b), j, 0)),
    ]
    inputs = [q, k, v]
    if has_segs:
        sq, sk = _seg_pads(seg_q, seg_k, q_pad, kv_pad)
        in_specs += [
            pl.BlockSpec((None, 1, block_q), lambda b, i, j, *_: (bq(b), 0, i)),
            pl.BlockSpec((None, 1, block_k), lambda b, i, j, *_: (bq(b), 0, j)),
        ]
        inputs += [sq, sk]
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((None, 1, block_q), lambda b, i, j, *_: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 1, q_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, lens, *inputs)
    return out[:, :q_len], lse


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "block_q",
                                             "block_k", "dropout_rate",
                                             "interpret", "n_heads",
                                             "kv_heads"))
def _flash_bwd(q, k, v, o, lse, g, seed, causal, sm_scale, block_q, block_k,
               dropout_rate=0.0, interpret=False, n_heads=1, kv_heads=1,
               lens=None, seg_q=None, seg_k=None):
    bh, q_len, d = q.shape
    kv_len = k.shape[1]
    kvm = _kv_map(n_heads, kv_heads)
    bq_map = lambda b: b // n_heads
    block_q, block_k = _norm_blocks(block_q, block_k, q_len, kv_len)
    q_pad = _round_up(q_len, block_q)
    kv_pad = _round_up(kv_len, block_k)

    # delta_i = rowsum(dO_i * O_i) — one fused elementwise pass in XLA
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = _pad_len(delta[:, None, :], q_pad, axis=2)         # [BH, 1, q_pad]
    qp = _pad_len(q, q_pad)
    gp = _pad_len(g, q_pad)
    kp = _pad_len(k, kv_pad)
    vp = _pad_len(v, kv_pad)
    # lse comes padded from fwd. Padded q rows are harmless in bwd because g
    # and delta are ZERO-padded: ds = p*(dp - delta) and the dv term both
    # vanish with do/delta = 0 — interior tiles rely on exactly this, they do
    # not mask. Keep the zero padding of gp/delta if this code changes.
    lsep = _pad_len(lse, q_pad, axis=2)

    has_lens = lens is not None
    has_segs = seg_q is not None
    if not has_lens:
        lens = jnp.zeros((1,), jnp.int32)
    seg_inputs = []
    if has_segs:
        sq, sk = _seg_pads(seg_q, seg_k, q_pad, kv_pad)
        seg_inputs = [sq, sk]

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
                  causal_offset=kv_len - q_len, dropout_rate=dropout_rate,
                  has_lens=has_lens, has_segs=has_segs, n_heads=n_heads)

    if q_pad == block_q and kv_pad == block_k:
        # whole sequence in one tile pair: fused dq/dk/dv kernel (computes
        # s/p once instead of twice across the dq and dkv kernels)
        fused_common = dict(common)
        fused_common.pop("grid4d", None)
        in_specs = [
            pl.BlockSpec((None, block_q, d), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, *_: (kvm(b), 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, *_: (kvm(b), 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, *_: (b, 0, 0)),
        ]
        if has_segs:
            in_specs += [
                pl.BlockSpec((None, 1, block_q),
                             lambda b, *_: (bq_map(b), 0, 0)),
                pl.BlockSpec((None, 1, block_k),
                             lambda b, *_: (bq_map(b), 0, 0)),
            ]
        dq, dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_fused_kernel, **fused_common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh,),
                in_specs=in_specs,
                out_specs=[
                    pl.BlockSpec((None, block_q, d), lambda b, *_: (b, 0, 0)),
                    pl.BlockSpec((None, block_k, d), lambda b, *_: (b, 0, 0)),
                    pl.BlockSpec((None, block_k, d), lambda b, *_: (b, 0, 0)),
                ],
            ),
            out_shape=[jax.ShapeDtypeStruct(qp.shape, q.dtype),
                       jax.ShapeDtypeStruct((bh,) + kp.shape[1:], k.dtype),
                       jax.ShapeDtypeStruct((bh,) + vp.shape[1:], v.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(seed, lens, qp, kp, vp, gp, lsep, delta, *seg_inputs)
        if kv_heads != n_heads:
            group = n_heads // kv_heads
            b_sz = bh // n_heads
            dk = dk.reshape(b_sz, kv_heads, group, kv_pad, d) \
                .astype(jnp.float32).sum(2) \
                .reshape(b_sz * kv_heads, kv_pad, d).astype(k.dtype)
            dv = dv.reshape(b_sz, kv_heads, group, kv_pad, d) \
                .astype(jnp.float32).sum(2) \
                .reshape(b_sz * kv_heads, kv_pad, d).astype(v.dtype)
        return dq[:, :q_len], dk[:, :kv_len], dv[:, :kv_len]

    dq_in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, i, j, *_: (kvm(b), j, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, i, j, *_: (kvm(b), j, 0)),
        pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((None, 1, block_q), lambda b, i, j, *_: (b, 0, i)),
        pl.BlockSpec((None, 1, block_q), lambda b, i, j, *_: (b, 0, i)),
    ]
    if has_segs:
        dq_in_specs += [
            pl.BlockSpec((None, 1, block_q),
                         lambda b, i, j, *_: (bq_map(b), 0, i)),
            pl.BlockSpec((None, 1, block_k),
                         lambda b, i, j, *_: (bq_map(b), 0, j)),
        ]
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, q_pad // block_q, kv_pad // block_k),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, lens, qp, kp, vp, gp, lsep, delta, *seg_inputs)

    # dk/dv are computed PER Q-HEAD (distinct grid rows may share a KV head
    # under GQA; parallel grid dims cannot accumulate into a shared output
    # block) and group-summed below in XLA.
    dkv_in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, j, i, *_: (b, i, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, j, i, *_: (kvm(b), j, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda b, j, i, *_: (kvm(b), j, 0)),
        pl.BlockSpec((None, block_q, d), lambda b, j, i, *_: (b, i, 0)),
        pl.BlockSpec((None, 1, block_q), lambda b, j, i, *_: (b, 0, i)),
        pl.BlockSpec((None, 1, block_q), lambda b, j, i, *_: (b, 0, i)),
    ]
    if has_segs:
        dkv_in_specs += [
            pl.BlockSpec((None, 1, block_q),
                         lambda b, j, i, *_: (bq_map(b), 0, i)),
            pl.BlockSpec((None, 1, block_k),
                         lambda b, j, i, *_: (bq_map(b), 0, j)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, kv_pad // block_k, q_pad // block_q),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((None, block_k, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, j, i, *_: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh,) + kp.shape[1:], k.dtype),
                   jax.ShapeDtypeStruct((bh,) + vp.shape[1:], v.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, lens, qp, kp, vp, gp, lsep, delta, *seg_inputs)

    if kv_heads != n_heads:
        group = n_heads // kv_heads
        b_sz = bh // n_heads
        # fp32 group reduction: bf16 accumulation over `group` per-head grads
        # would compound rounding the kernels avoid everywhere else
        dk = dk.reshape(b_sz, kv_heads, group, kv_pad, d) \
            .astype(jnp.float32).sum(2) \
            .reshape(b_sz * kv_heads, kv_pad, d).astype(k.dtype)
        dv = dv.reshape(b_sz, kv_heads, group, kv_pad, d) \
            .astype(jnp.float32).sum(2) \
            .reshape(b_sz * kv_heads, kv_pad, d).astype(v.dtype)

    return dq[:, :q_len], dk[:, :kv_len], dv[:, :kv_len]


# ---------------------------------------------------------------- packed qkv
# The fused qkv projection emits [B, L, 3*H*D]. When D is a lane multiple
# (128), Mosaic can tile a D-wide column block straight out of that buffer —
# so the kernels read Q at column h*D, K at (H+h)*D, V at (2H+h)*D over a
# (B, H, q_tile, kv_tile) grid and write the output pre-packed [B, L, H*D]
# for out_proj. No [B,S,3H] -> [B,S,3,H,D] -> [BH,S,D] relayout ever runs
# (profiled at ~0.3 ms per direction per layer as XLA copies).


@functools.partial(jax.jit, static_argnames=("heads", "head_dim", "causal",
                                             "sm_scale", "block_q", "block_k",
                                             "dropout_rate", "interpret"))
def _flash_fwd_packed(qkv, seed, heads, head_dim, causal, sm_scale,
                      block_q, block_k, dropout_rate=0.0, interpret=False):
    b, L, width = qkv.shape
    h, d = heads, head_dim
    assert width == 3 * h * d
    block_q, block_k = _norm_blocks(block_q, block_k, L, L)
    L_pad = _round_up(L, block_q)
    kv_pad = _round_up(L, block_k)
    pad = max(L_pad, kv_pad)
    qkv = _pad_len(qkv, pad)
    grid = (b, h, L_pad // block_q, kv_pad // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=L, kv_pad=kv_pad,
        causal_offset=0, dropout_rate=dropout_rate, grid4d=True)
    qs = pl.BlockSpec((None, block_q, d),
                      lambda bb, hh, i, j, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, block_k, d),
                      lambda bb, hh, i, j, *_: (bb, j, h + hh))
    vs = pl.BlockSpec((None, block_k, d),
                      lambda bb, hh, i, j, *_: (bb, j, 2 * h + hh))
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[qs, ks, vs],
            out_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda bb, hh, i, j, *_: (bb, i, hh)),
                pl.BlockSpec((None, None, 1, block_q),
                             lambda bb, hh, i, j, *_: (bb, hh, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, pad, h * d), qkv.dtype),
            jax.ShapeDtypeStruct((b, h, 1, L_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(seed, jnp.zeros((1,), jnp.int32), qkv, qkv, qkv)
    return out[:, :L], lse


@functools.partial(jax.jit, static_argnames=("heads", "head_dim", "causal",
                                             "sm_scale", "block_q", "block_k",
                                             "dropout_rate", "interpret"))
def _flash_bwd_packed(qkv, o, lse, g, seed, heads, head_dim, causal, sm_scale,
                      block_q, block_k, dropout_rate=0.0, interpret=False):
    b, L, width = qkv.shape
    h, d = heads, head_dim
    block_q, block_k = _norm_blocks(block_q, block_k, L, L)
    L_pad = _round_up(L, block_q)
    kv_pad = _round_up(L, block_k)
    pad = max(L_pad, kv_pad)
    qkvp = _pad_len(qkv, pad)
    gp = _pad_len(g, pad)

    # delta = rowsum(dO * O) per head: [B, L, H*D] -> [B, H, 1, L_pad]
    delta = jnp.sum((g.astype(jnp.float32) * o.astype(jnp.float32))
                    .reshape(b, L, h, d), axis=-1)
    delta = jnp.transpose(delta, (0, 2, 1))[:, :, None, :]
    delta = _pad_len(delta, L_pad, axis=3)
    lsep = _pad_len(lse, L_pad, axis=3)

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, kv_len=L, kv_pad=kv_pad, causal_offset=0,
                  dropout_rate=dropout_rate, grid4d=True)
    qs = pl.BlockSpec((None, block_q, d), lambda bb, hh, i, j, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, block_k, d),
                      lambda bb, hh, i, j, *_: (bb, j, h + hh))
    vs = pl.BlockSpec((None, block_k, d),
                      lambda bb, hh, i, j, *_: (bb, j, 2 * h + hh))
    gs = pl.BlockSpec((None, block_q, d), lambda bb, hh, i, j, *_: (bb, i, hh))
    ls = pl.BlockSpec((None, None, 1, block_q),
                      lambda bb, hh, i, j, *_: (bb, hh, 0, i))

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, L_pad // block_q, kv_pad // block_k),
            in_specs=[qs, ks, vs, gs, ls, ls],
            out_specs=pl.BlockSpec((None, block_q, d),
                                   lambda bb, hh, i, j, *_: (bb, i, hh)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((b, pad, h * d), qkv.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(seed, jnp.zeros((1,), jnp.int32), qkvp, qkvp, qkvp, gp, lsep, delta)

    # dkv grid: q innermost; kv-indexed specs use grid dim 2, q-indexed dim 3
    qs_i = pl.BlockSpec((None, block_q, d),
                        lambda bb, hh, j, i, *_: (bb, i, hh))
    ks_j = pl.BlockSpec((None, block_k, d),
                        lambda bb, hh, j, i, *_: (bb, j, h + hh))
    vs_j = pl.BlockSpec((None, block_k, d),
                        lambda bb, hh, j, i, *_: (bb, j, 2 * h + hh))
    gs_i = pl.BlockSpec((None, block_q, d),
                        lambda bb, hh, j, i, *_: (bb, i, hh))
    ls_i = pl.BlockSpec((None, None, 1, block_q),
                        lambda bb, hh, j, i, *_: (bb, hh, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, kv_pad // block_k, L_pad // block_q),
            in_specs=[qs_i, ks_j, vs_j, gs_i, ls_i, ls_i],
            out_specs=[
                pl.BlockSpec((None, block_k, d),
                             lambda bb, hh, j, i, *_: (bb, j, hh)),
                pl.BlockSpec((None, block_k, d),
                             lambda bb, hh, j, i, *_: (bb, j, hh)),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((b, pad, h * d), qkv.dtype),
                   jax.ShapeDtypeStruct((b, pad, h * d), qkv.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(seed, jnp.zeros((1,), jnp.int32), qkvp, qkvp, qkvp, gp, lsep, delta)

    # d(qkv): columns [dq | dk | dv]; the concat feeds qkv_proj's backward
    # matmul and fuses there
    return jnp.concatenate([dq[:, :L], dk[:, :L], dv[:, :L]], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def _flash_packed(qkv, seed, heads, head_dim, causal, sm_scale, block_q,
                  block_k, dropout_rate, interpret):
    out, _ = _flash_fwd_packed(qkv, seed, heads, head_dim, causal, sm_scale,
                               block_q, block_k, dropout_rate, interpret)
    return out


def _flash_packed_vjp_fwd(qkv, seed, heads, head_dim, causal, sm_scale,
                          block_q, block_k, dropout_rate, interpret):
    out, lse = _flash_fwd_packed(qkv, seed, heads, head_dim, causal, sm_scale,
                                 block_q, block_k, dropout_rate, interpret)
    return out, (qkv, out, lse, seed)


def _flash_packed_vjp_bwd(heads, head_dim, causal, sm_scale, block_q, block_k,
                          dropout_rate, interpret, res, g):
    qkv, out, lse, seed = res
    dqkv = _flash_bwd_packed(qkv, out, lse, g, seed, heads, head_dim, causal,
                             sm_scale, block_q, block_k, dropout_rate,
                             interpret)
    return dqkv, None


_flash_packed.defvjp(_flash_packed_vjp_fwd, _flash_packed_vjp_bwd)


def packed_layout_supported(head_dim: int) -> bool:
    """The one gate for the packed-qkv column layout: Mosaic lane-tiles the
    D-wide column blocks, so D must be a 128 multiple. Model code shares this
    predicate instead of restating the constant."""
    return head_dim % 128 == 0


def flash_attention_qkv_packed(qkv, num_heads, causal=True, sm_scale=None,
                               dropout_rate=0.0, seed=0,
                               block_q=None, block_k=None, interpret=False):
    """Flash attention straight off the fused projection: qkv [B, L, 3*H*D]
    (Q | K | V column blocks) -> [B, L, H*D], zero layout copies.
    Requires head_dim % 128 == 0 (Mosaic lane-tiles the column blocks)."""
    qkv = qkv.value() if hasattr(qkv, "value") else qkv
    b, L, width = qkv.shape
    if width % (3 * num_heads) != 0:
        raise ValueError(f"qkv width {width} != 3*H*D for H={num_heads}")
    d = width // (3 * num_heads)
    if not packed_layout_supported(d):
        raise ValueError(f"packed-qkv flash needs head_dim % 128 == 0 "
                         f"(got {d}); use flash_attention_blhd")
    if interpret and dropout_rate > 0.0:
        raise NotImplementedError(
            "in-kernel dropout uses the TPU hardware PRNG (pltpu.prng_*), "
            "which has no interpret-mode lowering; run on a real TPU or use "
            "dropout_rate=0.0 for CPU testing")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    seed_arr = jnp.atleast_1d(jnp.asarray(seed, jnp.int32))
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    return _flash_packed(qkv, seed_arr, int(num_heads), d, bool(causal),
                         float(sm_scale), block_q, block_k,
                         float(dropout_rate), bool(interpret))


def _reference_attention(q, k, v, causal, sm_scale):
    # [BH, L, D]; fp32 math — correctness oracle for tests
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        ql, kl = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if causal:
        # rows with zero valid keys → 0 output (kernel semantics), not uniform avg
        p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bqk,bkd->bqd", p, vf).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12, 13, 14))
def _flash(q, k, v, seed, lens, seg_q, seg_k, causal, sm_scale, block_q,
           block_k, dropout_rate, interpret, n_heads=1, kv_heads=1):
    out, _ = _flash_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
                        dropout_rate, interpret, n_heads, kv_heads,
                        lens=lens, seg_q=seg_q, seg_k=seg_k)
    return out


def _flash_vjp_fwd(q, k, v, seed, lens, seg_q, seg_k, causal, sm_scale,
                   block_q, block_k, dropout_rate, interpret, n_heads,
                   kv_heads):
    out, lse = _flash_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
                          dropout_rate, interpret, n_heads, kv_heads,
                          lens=lens, seg_q=seg_q, seg_k=seg_k)
    return out, (q, k, v, out, lse, seed, lens, seg_q, seg_k)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, dropout_rate, interpret,
                   n_heads, kv_heads, res, g):
    q, k, v, out, lse, seed, lens, seg_q, seg_k = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, seed, causal, sm_scale,
                            block_q, block_k, dropout_rate, interpret,
                            n_heads, kv_heads,
                            lens=lens, seg_q=seg_q, seg_k=seg_k)
    return dq, dk, dv, None, None, None, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _tuned_blocks(bh, lq, lk, d, dtype, causal, sm_scale, dropout_rate):
    """Pick (block_q, block_k) via the measured autotune cache
    (kernels/autotune; reference: phi/kernels/autotune switch + cache).

    Measurement synthesizes sample arrays from the shape signature, so it
    works at trace time too (the flagship path hits this inside jit, where
    the real operands are tracers). Forward-kernel time is the selection
    metric; bwd shares the config through the custom_vjp's nondiff args."""
    from ..autotune import autotune_pick
    import numpy as np

    key = (bh, lq, lk, d, str(dtype), int(causal), int(dropout_rate > 0))
    # per-axis candidates, deduped through the same clamp the kernel applies
    # (a 128-long axis collapses every size to one real kernel)
    sizes_q = [s for s in (256, 512, 1024) if s <= lq] or [256]
    sizes_k = [s for s in (256, 512, 1024) if s <= lk] or [256]
    cands = sorted({_norm_blocks(bq, bk, lq, lk)
                    for bq in sizes_q for bk in sizes_k})
    if len(cands) == 1:
        return cands[0]  # nothing to measure
    sample = [None]  # lazily allocated once, only on a cache miss

    def measure(cand):
        if sample[0] is None:
            rs = np.random.RandomState(0)
            qm = jnp.asarray(rs.randn(bh, lq, d), dtype)
            km = jnp.asarray(rs.randn(bh, lk, d), dtype)
            vm = jnp.asarray(rs.randn(bh, lk, d), dtype)
            sample[0] = (qm, km, vm, jnp.asarray([0], jnp.int32))
        qm, km, vm, sd = sample[0]
        bq, bk = cand

        def run():
            out = _flash(qm, km, vm, sd, None, None, None, causal, sm_scale,
                         bq, bk, float(dropout_rate), False)
            jax.block_until_ready(out)
        return run

    return autotune_pick("flash_attention", key, cands, measure)


def flash_attention_blhd(q, k, v, causal=False, sm_scale=None,
                         dropout_rate=0.0, seed=0,
                         block_q=None, block_k=None,
                         interpret=False,
                         kv_lens=None, q_segments=None, kv_segments=None):
    """Flash attention on [B, L, H, D] arrays (jax.Array or Tensor-like .value()).

    kv_lens ([B] int32): per-sequence key counts — encoder padding-mask
    attention (keys at positions >= kv_lens[b] are never attended; queries
    keep attending the valid keys, matching additive-mask semantics).
    q_segments/kv_segments ([B, L] int32): packed-sequence ids — only
    same-segment pairs attend. Reference: phi/kernels/flash_attn_kernel.h
    serves encoder (padded/packed) and decoder attention alike.

    block_q/block_k default to the autotuned choice when FLAGS_use_autotune is
    on (persistent measured cache), else DEFAULT_BLOCK_Q/K."""
    unwrap = lambda t: t.value() if hasattr(t, "value") else t
    q, k, v = unwrap(q), unwrap(k), unwrap(v)
    if kv_lens is not None:
        kv_lens = jnp.asarray(unwrap(kv_lens), jnp.int32)
    if (q_segments is None) != (kv_segments is None):
        raise ValueError("q_segments and kv_segments must be given together")
    if q_segments is not None:
        q_segments = jnp.asarray(unwrap(q_segments), jnp.int32)
        kv_segments = jnp.asarray(unwrap(kv_segments), jnp.int32)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    hkv = k.shape[2]
    if h % hkv != 0 or v.shape[2] != hkv:
        raise ValueError(f"GQA needs kv heads dividing q heads and matching "
                         f"k/v; got q:{h} k:{k.shape[2]} v:{v.shape[2]}")
    if interpret and dropout_rate > 0.0:
        raise NotImplementedError(
            "in-kernel dropout uses the TPU hardware PRNG (pltpu.prng_*), which "
            "has no interpret-mode lowering; run on a real TPU or use "
            "dropout_rate=0.0 / the XLA sdpa path for CPU testing")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    to_flat = lambda t, L, hh: jnp.swapaxes(t, 1, 2).reshape(b * hh, L, d)
    qr = to_flat(q, lq, h)
    kr = to_flat(k, lk, hkv)
    vr = to_flat(v, lk, hkv)
    seed_arr = jnp.atleast_1d(jnp.asarray(seed, jnp.int32))
    if block_q is None or block_k is None:
        from ...core.flags import flag
        tb = None
        if flag("FLAGS_use_autotune") and not interpret:
            tb = _tuned_blocks(b * h, lq, lk, d, q.dtype, bool(causal),
                               float(sm_scale), float(dropout_rate))
        block_q = block_q or (tb[0] if tb else DEFAULT_BLOCK_Q)
        block_k = block_k or (tb[1] if tb else DEFAULT_BLOCK_K)
    out = _flash(qr, kr, vr, seed_arr, kv_lens, q_segments, kv_segments,
                 bool(causal), float(sm_scale),
                 block_q, block_k, float(dropout_rate), bool(interpret),
                 h, hkv)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)
