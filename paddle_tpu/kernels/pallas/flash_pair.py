"""Head-PAIR flash attention over the packed qkv layout, for head_dim 64.

Why this exists: at head_dim 64 (GPT-medium, BERT-base, most 64-dim-head
models) the flat [B*H, L, D] kernels read half-empty 128-lane tiles AND the
[B,L,H,D] <-> [B*H,L,D] relayout around them costs ~4 ms/layer of pure HBM
transposes at BERT-base shapes (measured, BASELINE.md r4). This path instead
reads 128-wide column blocks straight out of the fused projection output
[B, L, 3*H*D] — TWO adjacent 64-wide heads per block — and writes the
context back pre-packed [B, L, H*D]. Zero layout copies, full lanes.

Shape contract: head-BLOCKS of hpb = max(1, 128 // head_dim) adjacent heads
fill the 128-lane quantum (hpb*d % 128 == 0; hpb=2 at d=64, hpb=1 at d=128)
and num_heads % hpb == 0. Any sequence length: the forward streams KV tiles
with online-softmax carries (m/l/acc scratch across the kv grid dim), and
the backward picks between two forms by VMEM budget:

  - FUSED (kv_pad <= 4096): one kernel, s/p computed once per tile for dq,
    dk AND dv; dk/dv accumulate in full-length VMEM scratch across both
    grid dims (the scratch is what bounds the length).
  - SPLIT (longer): the classic two-kernel flash backward — a dq kernel
    (q-parallel, kv streamed) and a dkv kernel (kv-parallel, q streamed),
    each with only tile-sized scratch, so any length fits; s/p recomputed
    per kernel.

Both write d(qkv) parts directly in the packed layout — zero relayouts at
every length.

Reference analog: phi/kernels/fusion/fused_attention — the reference fuses
qkv-projection-adjacent attention exactly to avoid these relayouts.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (_NEG_INF, _dropout_mask, _pad_len, _round_up,
                              _valid_mask)


def _heads_per_block(head_dim: int) -> int:
    """How many adjacent heads fill the 128-lane quantum (2 at d=64, 1 at
    d>=128-multiples)."""
    return max(1, 128 // head_dim)


# longest kv_pad the FUSED backward's full-length dk/dv scratch fits in VMEM
# (2 x kv_pad x (hpb*d) lanes x 4 B = 4 MB at kv_pad=4096, hpb*d=128, which
# fits with the reduced 256/512 tiles — see _pair_bwd; the split form takes
# over beyond). The budget was sized at hpb*d == 128 lanes: head_dim=256
# passes pair_layout_supported (256 % 128 == 0) with hpb*d == 256, doubling
# the scratch — so the cutoff scales down by the same lane factor instead of
# blowing past VMEM at kv_pad=4096 (ADVICE r5).
_MAX_FUSED_BWD_LANE_BUDGET = 4096 * 128


def _max_fused_bwd(hpb: int, d: int, override=None) -> int:
    """Fused-bwd kv_pad cutoff. The heuristic (lane budget / lane width)
    loses to reality on chips with other VMEM headroom — override with the
    ``max_fused_bwd=`` kwarg (flash_pair_packed) or env
    ``PADDLE_FLASH_FUSED_BWD_MAX=<kv_pad>`` (0 forces the split form).
    The env fallback here runs when a backward first TRACES a static
    signature; like anything read into a compiled program, a mid-process
    env change only affects new signatures (flash_pair_packed resolves the
    env at the call site instead, so its callers re-trace on change —
    direct flash_pair callers wanting a per-call value must pass the
    kwarg)."""
    if override is None:
        env = os.environ.get("PADDLE_FLASH_FUSED_BWD_MAX")
        if env:
            override = int(env)
    if override is not None:
        return int(override)
    return _MAX_FUSED_BWD_LANE_BUDGET // (hpb * d)


def pair_layout_supported(head_dim: int, num_heads: int,
                          seq_len: int = 0) -> bool:
    """The gate for this path: whole head-blocks fill the 128-lane quantum.
    Any sequence length (round 5: multi-tile online-softmax kernels; the
    seq_len parameter remains for call-site compatibility)."""
    hpb = _heads_per_block(head_dim)
    return ((hpb * head_dim) % 128 == 0 and head_dim % 8 == 0
            and num_heads % hpb == 0)


# ------------------------------------------------------------------ forward


def _pair_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                     m_sc, l_sc, acc_sc, *,
                     sm_scale, causal, d, kv_len, block_q, block_k, n_k,
                     dropout_rate, n_heads, hpb):
    # grid (b, head_block, q_blocks, kv_blocks); kv innermost/sequential —
    # m/l/acc carry the online softmax across kv tiles in scratch. Refs hold
    # hpb heads side by side [*, hpb*d].
    b, h2 = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)

    if n_k == 1:
        # single-KV-tile fast path (the pre-round-5 kernel): softmax in
        # registers, no online-softmax scratch round trips — this is the
        # production config for L <= 1024 (GPT-medium bench, BERT-512)
        for which in range(hpb):
            sl = slice(which * d, (which + 1) * d)
            qs = (q_ref[:, sl].astype(jnp.float32)
                  * sm_scale).astype(q_ref.dtype)
            s = jax.lax.dot_general(qs, k_ref[:, sl],
                                    (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            valid = None
            if causal or kv_len < block_k:
                valid = _valid_mask(qi, 0, causal=causal, block_q=block_q,
                                    block_k=block_k, kv_len=kv_len,
                                    causal_offset=0)
                s = jnp.where(valid, s, _NEG_INF)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            if valid is not None:
                p = jnp.where(valid, p, 0.0)
            l = jnp.sum(p, axis=-1, keepdims=True)
            if dropout_rate > 0.0:
                bh = b * n_heads + hpb * h2 + which
                keep = _dropout_mask(seed_ref, bh, qi, jnp.int32(0),
                                     (block_q, block_k), dropout_rate)
                p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            o = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[:, sl],
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            o_ref[:, sl] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
            lse_ref[which, :] = (m[:, 0]
                                 + jnp.log(jnp.maximum(l[:, 0], 1e-30)))
        return

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: tiles fully above the diagonal contribute nothing
    def _body():
        for which in range(hpb):
            sl = slice(which * d, (which + 1) * d)
            qs = (q_ref[:, sl].astype(jnp.float32)
                  * sm_scale).astype(q_ref.dtype)
            s = jax.lax.dot_general(qs, k_ref[:, sl],
                                    (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            valid = None
            if causal or kv_len < n_k * block_k:
                valid = _valid_mask(qi, ki, causal=causal, block_q=block_q,
                                    block_k=block_k, kv_len=kv_len,
                                    causal_offset=0)
                s = jnp.where(valid, s, _NEG_INF)
            m_prev = m_sc[which, :]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            corr = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur[:, None])
            if valid is not None:
                p = jnp.where(valid, p, 0.0)
            l_sc[which, :] = l_sc[which, :] * corr + jnp.sum(p, axis=-1)
            m_sc[which, :] = m_cur
            if dropout_rate > 0.0:
                bh = b * n_heads + hpb * h2 + which
                keep = _dropout_mask(seed_ref, bh, qi, ki,
                                     (block_q, block_k), dropout_rate)
                p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[:, sl],
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            acc_sc[:, sl] = acc_sc[:, sl] * corr[:, None] + pv

    if causal:
        # tiles fully above the diagonal contribute nothing — skip them
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        for which in range(hpb):
            sl = slice(which * d, (which + 1) * d)
            l = jnp.maximum(l_sc[which, :], 1e-30)
            o_ref[:, sl] = (acc_sc[:, sl] / l[:, None]).astype(o_ref.dtype)
            lse_ref[which, :] = m_sc[which, :] + jnp.log(l)


def _norm_pair_blocks(L, block_q, block_k):
    kv_pad = _round_up(L, 128)
    if kv_pad > 2048:
        # ONE tile geometry shared by forward and backward at every length:
        # the dropout PRNG seeds per (q-tile, kv-tile), so fwd/bwd tile
        # shapes must match or the keep masks desynchronize. The 256/512
        # tiles are what lets the fused backward's full-length scratch fit
        # VMEM at 4096 (512/1024 measured 16.52 MB vs the 16 MB budget).
        block_q = min(block_q, 256)
        block_k = min(block_k, 512)
    block_q = min(block_q, kv_pad)
    while kv_pad % block_q:      # q blocks must tile the padded row count
        block_q //= 2
    block_k = min(block_k, kv_pad)
    while kv_pad % block_k:
        block_k //= 2
    return kv_pad, block_q, block_k


@functools.partial(jax.jit, static_argnames=("heads", "d", "causal",
                                             "sm_scale", "block_q",
                                             "dropout_rate", "interpret"))
def _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
              dropout_rate=0.0, interpret=False):
    b, L, width = qkv.shape
    hpb = _heads_per_block(d)
    h2 = heads // hpb
    kv_pad, block_q, block_k = _norm_pair_blocks(L, block_q, 1024)
    q_pad = kv_pad
    n_k = kv_pad // block_k
    qkvp = _pad_len(qkv, kv_pad)
    grid = (b, h2, q_pad // block_q, n_k)
    # column maps into [B, L, 3HD]: q block at hpb*h2*d, k at (H + hpb*h2)*d
    qs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, block_k, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, j, h2 + hh))
    vs = pl.BlockSpec((None, block_k, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, j, 2 * h2 + hh))
    out, lse = pl.pallas_call(
        functools.partial(_pair_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          d=d, kv_len=L, block_q=block_q, block_k=block_k,
                          n_k=n_k, dropout_rate=dropout_rate, n_heads=heads,
                          hpb=hpb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[qs, ks, vs],
            out_specs=[
                pl.BlockSpec((None, block_q, hpb * d),
                             lambda bb, hh, i, j, *_: (bb, i, hh)),
                pl.BlockSpec((None, None, hpb, block_q),
                             lambda bb, hh, i, j, *_: (bb, hh, 0, i)),
            ],
            scratch_shapes=[pltpu.VMEM((hpb, block_q), jnp.float32),
                            pltpu.VMEM((hpb, block_q), jnp.float32),
                            pltpu.VMEM((block_q, hpb * d), jnp.float32)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, kv_pad, heads * d), qkv.dtype),
            jax.ShapeDtypeStruct((b, h2, hpb, q_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(seed, qkvp, qkvp, qkvp)
    return out[:, :L], lse


# ------------------------------------------------------------------ backward


def _bwd_tile_core(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   which, qi, ki, *, sm_scale, causal, d, kv_len, block_q,
                   block_k, dropout_rate, n_heads, hpb, b, h2):
    """Recompute p and the shared ds for one (head, q-tile, kv-tile); returns
    (p_dv, do, dsc) for the caller's dq/dk/dv matmuls. Identical math in the
    fused and split kernels so their gradients can never diverge."""
    sl = slice(which * d, (which + 1) * d)
    qs = (q_ref[:, sl].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
    s = jax.lax.dot_general(qs, k_ref[:, sl], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    lse = lse_ref[which, :][:, None]
    p = jnp.exp(s - lse)
    valid = _valid_mask(qi, ki, causal=causal, block_q=block_q,
                        block_k=block_k, kv_len=kv_len, causal_offset=0)
    p = jnp.where(valid, p, 0.0)
    keep_scale = None
    if dropout_rate > 0.0:
        bh = b * n_heads + hpb * h2 + which
        keep = _dropout_mask(seed_ref, bh, qi, ki, (block_q, block_k),
                             dropout_rate)
        keep_scale = jnp.where(keep, 1.0 / (1.0 - dropout_rate), 0.0)
    do = do_ref[:, sl]
    p_dv = p * keep_scale if keep_scale is not None else p
    dp = jax.lax.dot_general(do, v_ref[:, sl], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if keep_scale is not None:
        dp = dp * keep_scale
    ds = p * (dp - delta_ref[which, :][:, None])
    return sl, p_dv, do, ds.astype(q_ref.dtype)


def _pair_bwd_fused_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dq_ref, dk_ref, dv_ref,
                           dq_acc, dk_acc, dv_acc, *,
                           sm_scale, causal, d, kv_len, block_q, block_k,
                           dropout_rate, n_heads, n_q, n_k, hpb):
    # grid (b, h2, q_blocks, kv_blocks), both inner dims sequential. s/p
    # computed ONCE per (pair, q-tile, kv-tile) for dq, dk AND dv: dq
    # accumulates across kv tiles in a small scratch, dk/dv accumulate
    # across BOTH dims in full-length scratch (what bounds kv_pad <= 4 k).
    b, h2 = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(jnp.logical_and(qi == 0, ki == 0))
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if n_k > 1:
        @pl.when(ki == 0)
        def _init_q():
            dq_acc[:] = jnp.zeros_like(dq_acc)

    def _body():
        for which in range(hpb):
            sl, p_dv, do, dsc = _bwd_tile_core(
                seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                which, qi, ki, sm_scale=sm_scale, causal=causal, d=d,
                kv_len=kv_len, block_q=block_q, block_k=block_k,
                dropout_rate=dropout_rate, n_heads=n_heads, hpb=hpb,
                b=b, h2=h2)
            dq = jax.lax.dot_general(
                dsc, k_ref[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if n_k == 1:
                # single KV tile: dq complete in this step — write direct,
                # no accumulator round trip (the pre-round-5 form)
                dq_ref[pl.ds(qi * block_q, block_q), sl] = \
                    dq.astype(dq_ref.dtype)
            else:
                dq_acc[:, sl] += dq
            rows = pl.ds(ki * block_k, block_k)
            dv_acc[rows, sl] += jax.lax.dot_general(
                p_dv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc[rows, sl] += jax.lax.dot_general(
                dsc, q_ref[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale

    if causal:
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    if n_k > 1:
        @pl.when(ki == n_k - 1)
        def _write_dq():
            dq_ref[pl.ds(qi * block_q, block_q), :] = \
                dq_acc[:].astype(dq_ref.dtype)

    @pl.when(jnp.logical_and(qi == n_q - 1, ki == n_k - 1))
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _pair_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, dq_acc, *,
                        sm_scale, causal, d, kv_len, block_q, block_k,
                        dropout_rate, n_heads, n_k, hpb):
    # split form, kernel 1: grid (b, h2, q_blocks, kv_blocks), kv streamed —
    # only tile-sized scratch, so any sequence length fits
    b, h2 = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _body():
        for which in range(hpb):
            sl, _p_dv, _do, dsc = _bwd_tile_core(
                seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                which, qi, ki, sm_scale=sm_scale, causal=causal, d=d,
                kv_len=kv_len, block_q=block_q, block_k=block_k,
                dropout_rate=dropout_rate, n_heads=n_heads, hpb=hpb,
                b=b, h2=h2)
            dq_acc[:, sl] += jax.lax.dot_general(
                dsc, k_ref[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale

    if causal:
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _write():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


def _pair_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                         sm_scale, causal, d, kv_len, block_q, block_k,
                         dropout_rate, n_heads, n_q, hpb):
    # split form, kernel 2: grid (b, h2, kv_blocks, q_blocks), q streamed
    b, h2 = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _body():
        for which in range(hpb):
            sl, p_dv, do, dsc = _bwd_tile_core(
                seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                which, qi, ki, sm_scale=sm_scale, causal=causal, d=d,
                kv_len=kv_len, block_q=block_q, block_k=block_k,
                dropout_rate=dropout_rate, n_heads=n_heads, hpb=hpb,
                b=b, h2=h2)
            dv_acc[:, sl] += jax.lax.dot_general(
                p_dv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc[:, sl] += jax.lax.dot_general(
                dsc, q_ref[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale

    if causal:
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(qi == n_q - 1)
    def _write():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("heads", "d", "causal",
                                             "sm_scale", "block_q",
                                             "dropout_rate", "interpret",
                                             "max_fused_bwd"))
def _pair_bwd(qkv, o, lse, g, seed, heads, d, causal, sm_scale, block_q,
              dropout_rate=0.0, interpret=False, max_fused_bwd=None):
    b, L, width = qkv.shape
    hpb = _heads_per_block(d)
    h2 = heads // hpb
    kv_pad, block_q, block_k = _norm_pair_blocks(L, block_q, 1024)
    q_pad = kv_pad
    n_q, n_k = q_pad // block_q, kv_pad // block_k
    qkvp = _pad_len(qkv, kv_pad)
    gp = _pad_len(g, kv_pad)
    delta = jnp.sum((g.astype(jnp.float32) * o.astype(jnp.float32))
                    .reshape(b, L, heads, d), axis=-1)       # [B, L, H]
    delta = jnp.transpose(delta, (0, 2, 1)).reshape(b, h2, hpb, L)
    delta = _pad_len(delta, q_pad, axis=3)
    lsep = _pad_len(lse, q_pad, axis=3)

    qs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, block_k, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, j, h2 + hh))
    vs = pl.BlockSpec((None, block_k, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, j, 2 * h2 + hh))
    gs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, j, *_: (bb, i, hh))
    ls = pl.BlockSpec((None, None, hpb, block_q),
                      lambda bb, hh, i, j, *_: (bb, hh, 0, i))
    common = dict(sm_scale=sm_scale, causal=causal, d=d, kv_len=L,
                  block_q=block_q, block_k=block_k,
                  dropout_rate=dropout_rate, n_heads=heads, hpb=hpb)

    if kv_pad <= _max_fused_bwd(hpb, d, max_fused_bwd):
        # FUSED: s/p once per tile for all three grads
        gpart = pl.BlockSpec((None, kv_pad, hpb * d),
                             lambda bb, hh, i, j, *_: (bb, 0, hh))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_pair_bwd_fused_kernel, n_q=n_q, n_k=n_k,
                              **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, h2, n_q, n_k),
                in_specs=[qs, ks, vs, gs, ls, ls],
                out_specs=[gpart, gpart, gpart],
                scratch_shapes=[
                    pltpu.VMEM((block_q, hpb * d), jnp.float32),
                    pltpu.VMEM((kv_pad, hpb * d), jnp.float32),
                    pltpu.VMEM((kv_pad, hpb * d), jnp.float32)],
            ),
            out_shape=[jax.ShapeDtypeStruct((b, kv_pad, heads * d),
                                            qkv.dtype) for _ in range(3)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary",
                                     "arbitrary")),
            interpret=interpret,
        )(seed, qkvp, qkvp, qkvp, gp, lsep, delta)
    else:
        # SPLIT: tile-sized scratch only — any length; s/p recomputed per
        # kernel (the same trade the flat long-context kernels make)
        dq, = pl.pallas_call(
            functools.partial(_pair_bwd_dq_kernel, n_k=n_k, **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, h2, n_q, n_k),
                in_specs=[qs, ks, vs, gs, ls, ls],
                out_specs=[pl.BlockSpec((None, block_q, hpb * d),
                                        lambda bb, hh, i, j, *_: (bb, i, hh))],
                scratch_shapes=[pltpu.VMEM((block_q, hpb * d), jnp.float32)],
            ),
            out_shape=[jax.ShapeDtypeStruct((b, kv_pad, heads * d),
                                            qkv.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(seed, qkvp, qkvp, qkvp, gp, lsep, delta)
        qs2 = pl.BlockSpec((None, block_q, hpb * d),
                           lambda bb, hh, j, i, *_: (bb, i, hh))
        ks2 = pl.BlockSpec((None, block_k, hpb * d),
                           lambda bb, hh, j, i, *_: (bb, j, h2 + hh))
        vs2 = pl.BlockSpec((None, block_k, hpb * d),
                           lambda bb, hh, j, i, *_: (bb, j, 2 * h2 + hh))
        gs2 = pl.BlockSpec((None, block_q, hpb * d),
                           lambda bb, hh, j, i, *_: (bb, i, hh))
        ls2 = pl.BlockSpec((None, None, hpb, block_q),
                           lambda bb, hh, j, i, *_: (bb, hh, 0, i))
        dkv_spec = pl.BlockSpec((None, block_k, hpb * d),
                                lambda bb, hh, j, i, *_: (bb, j, hh))
        dk, dv = pl.pallas_call(
            functools.partial(_pair_bwd_dkv_kernel, n_q=n_q, **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, h2, n_k, n_q),
                in_specs=[qs2, ks2, vs2, gs2, ls2, ls2],
                out_specs=[dkv_spec, dkv_spec],
                scratch_shapes=[
                    pltpu.VMEM((block_k, hpb * d), jnp.float32),
                    pltpu.VMEM((block_k, hpb * d), jnp.float32)],
            ),
            out_shape=[jax.ShapeDtypeStruct((b, kv_pad, heads * d),
                                            qkv.dtype) for _ in range(2)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(seed, qkvp, qkvp, qkvp, gp, lsep, delta)
    # d(qkv) column order [q | k | v]; the concat feeds qkv_proj's backward
    # matmul and fuses there
    return jnp.concatenate([dq[:, :L], dk[:, :L], dv[:, :L]], axis=-1)


# ------------------------------------------------------------------ custom_vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def flash_pair(qkv, seed, heads, d, causal, sm_scale, block_q, dropout_rate,
               interpret, max_fused_bwd=None):
    out, _ = _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                       dropout_rate, interpret)
    return out


def _pair_vjp_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                  dropout_rate, interpret, max_fused_bwd=None):
    out, lse = _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                         dropout_rate, interpret)
    return out, (qkv, out, lse, seed)


def _pair_vjp_bwd(heads, d, causal, sm_scale, block_q, dropout_rate,
                  interpret, max_fused_bwd, res, g):
    qkv, out, lse, seed = res
    dqkv = _pair_bwd(qkv, out, lse, g, seed, heads, d, causal, sm_scale,
                     block_q, dropout_rate, interpret,
                     max_fused_bwd=max_fused_bwd)
    return dqkv, None


flash_pair.defvjp(_pair_vjp_fwd, _pair_vjp_bwd)


def flash_pair_packed(qkv, num_heads, causal, dropout_rate=0.0, seed=0,
                      block_q=512, interpret=False, max_fused_bwd=None):
    """Keyword front door for the pair path: derives head_dim/scale/seed form
    so call sites don't hand-assemble the positional custom_vjp call.
    ``max_fused_bwd`` overrides the fused-backward kv_pad cutoff (see
    _max_fused_bwd; env PADDLE_FLASH_FUSED_BWD_MAX works everywhere)."""
    d = qkv.shape[-1] // (3 * num_heads)
    if not pair_layout_supported(d, num_heads, qkv.shape[1]):
        # fail fast: a truncating heads // hpb would leave trailing heads'
        # output columns unwritten (silent NaN/garbage)
        raise ValueError(
            f"flash_pair: unsupported shape (head_dim={d}, "
            f"num_heads={num_heads}); requires "
            f"num_heads % max(1, 128 // head_dim) == 0 and hpb*d % 128 == 0 "
            f"— use flash_attention_blhd/packed instead")
    if max_fused_bwd is None:
        # resolve the env HERE, outside any jit: max_fused_bwd is a static
        # argname of the jitted _pair_bwd, so an env read at trace time
        # would be frozen into the cached executable — resolving at the
        # front door makes a changed env a new static value (fresh trace)
        env = os.environ.get("PADDLE_FLASH_FUSED_BWD_MAX")
        if env:
            max_fused_bwd = int(env)
    seed_arr = jnp.atleast_1d(jnp.asarray(seed, jnp.int32))
    return flash_pair(qkv, seed_arr, int(num_heads), int(d), bool(causal),
                      1.0 / math.sqrt(d), int(block_q), float(dropout_rate),
                      bool(interpret),
                      None if max_fused_bwd is None else int(max_fused_bwd))
