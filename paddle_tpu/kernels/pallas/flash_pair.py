"""Head-PAIR flash attention over the packed qkv layout, for head_dim 64.

Why this exists: at head_dim 64 (GPT-medium, BERT-base, most 64-dim-head
models) the flat [B*H, L, D] kernels read half-empty 128-lane tiles AND the
[B,L,H,D] <-> [B*H,L,D] relayout around them costs ~4 ms/layer of pure HBM
transposes at BERT-base shapes (measured, BASELINE.md r4). This path instead
reads 128-wide column blocks straight out of the fused projection output
[B, L, 3*H*D] — TWO adjacent 64-wide heads per block — and writes the
context back pre-packed [B, L, H*D]. Zero layout copies, full lanes.

Shape contract: head-BLOCKS of hpb = max(1, 128 // head_dim) adjacent heads
fill the 128-lane quantum (hpb*d % 128 == 0; hpb=2 at d=64, hpb=1 at d=128),
num_heads % hpb == 0, and the whole KV length in ONE tile (L_pad == block_k;
VMEM bounds this to L <= ~1024). Within that contract the backward is the
fused single-tile form (s/p computed once for dq, dk AND dv — see
_flash_bwd_fused_kernel's rationale) writing d(qkv) parts directly in the
packed layout — so d=128 decoders get the fused backward through this path
too.

Reference analog: phi/kernels/fusion/fused_attention — the reference fuses
qkv-projection-adjacent attention exactly to avoid these relayouts.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (_NEG_INF, _dropout_mask, _pad_len, _round_up,
                              _valid_mask)


def _heads_per_block(head_dim: int) -> int:
    """How many adjacent heads fill the 128-lane quantum (2 at d=64, 1 at
    d>=128-multiples)."""
    return max(1, 128 // head_dim)


def pair_layout_supported(head_dim: int, num_heads: int, seq_len: int) -> bool:
    """The gate for this path: whole head-blocks fill the 128-lane quantum,
    and the KV length fits one tile (scores stay in VMEM)."""
    hpb = _heads_per_block(head_dim)
    return ((hpb * head_dim) % 128 == 0 and head_dim % 8 == 0
            and num_heads % hpb == 0 and seq_len <= 1024)


# ------------------------------------------------------------------ forward


def _pair_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                     sm_scale, causal, d, kv_len, block_q, kv_pad,
                     dropout_rate, n_heads, hpb):
    # grid (b, head_block, q_blocks); refs hold hpb heads side by side [*, hpb*d]
    b, h2, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    for which in range(hpb):
        sl = slice(which * d, (which + 1) * d)
        qs = (q_ref[:, sl].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(qs, k_ref[:, sl], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        valid = None
        if causal or kv_len < kv_pad:
            valid = _valid_mask(qi, 0, causal=causal, block_q=block_q,
                                block_k=kv_pad, kv_len=kv_len,
                                causal_offset=0)
            s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            bh = b * n_heads + hpb * h2 + which
            keep = _dropout_mask(seed_ref, bh, qi, jnp.int32(0),
                                 (block_q, kv_pad), dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        o = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[:, sl],
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[:, sl] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[which, :] = (m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)))


@functools.partial(jax.jit, static_argnames=("heads", "d", "causal",
                                             "sm_scale", "block_q",
                                             "dropout_rate", "interpret"))
def _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
              dropout_rate=0.0, interpret=False):
    b, L, width = qkv.shape
    hpb = _heads_per_block(d)
    h2 = heads // hpb
    kv_pad = _round_up(L, 128)
    block_q = min(block_q, kv_pad)
    while kv_pad % block_q:      # q blocks must tile the kv row count exactly
        block_q //= 2
    q_pad = kv_pad
    qkvp = _pad_len(qkv, kv_pad)
    grid = (b, h2, q_pad // block_q)
    # column maps into [B, L, 3HD]: q block at hpb*h2*d, k at (H + hpb*h2)*d
    qs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, kv_pad, hpb * d),
                      lambda bb, hh, i, *_: (bb, 0, h2 + hh))
    vs = pl.BlockSpec((None, kv_pad, hpb * d),
                      lambda bb, hh, i, *_: (bb, 0, 2 * h2 + hh))
    out, lse = pl.pallas_call(
        functools.partial(_pair_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          d=d, kv_len=L, block_q=block_q, kv_pad=kv_pad,
                          dropout_rate=dropout_rate, n_heads=heads, hpb=hpb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[qs, ks, vs],
            out_specs=[
                pl.BlockSpec((None, block_q, hpb * d),
                             lambda bb, hh, i, *_: (bb, i, hh)),
                pl.BlockSpec((None, None, hpb, block_q),
                             lambda bb, hh, i, *_: (bb, hh, 0, i)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, kv_pad, heads * d), qkv.dtype),
            jax.ShapeDtypeStruct((b, h2, hpb, q_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        interpret=interpret,
    )(seed, qkvp, qkvp, qkvp)
    return out[:, :L], lse


# ------------------------------------------------------------------ backward


def _pair_bwd_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                     sm_scale, causal, d, kv_len, block_q, kv_pad,
                     dropout_rate, n_heads, n_q, hpb):
    # grid (b, h2, q_blocks) with q sequential. dq/dk/dv are separate
    # kv_pad-tall 2D-blocked outputs (Mosaic-friendly refs): dq rows land per
    # q block via a dynamic-slice store; dk/dv accumulate in scratch and
    # finalize at the last q step. s/p computed ONCE per (pair, q block).
    b, h2, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    for which in range(hpb):
        sl = slice(which * d, (which + 1) * d)
        qs = (q_ref[:, sl].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(qs, k_ref[:, sl], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[which, :][:, None]
        p = jnp.exp(s - lse)
        if causal or kv_len < kv_pad:
            valid = _valid_mask(qi, 0, causal=causal, block_q=block_q,
                                block_k=kv_pad, kv_len=kv_len,
                                causal_offset=0)
            p = jnp.where(valid, p, 0.0)
        keep_scale = None
        if dropout_rate > 0.0:
            bh = b * n_heads + hpb * h2 + which
            keep = _dropout_mask(seed_ref, bh, qi, jnp.int32(0),
                                 (block_q, kv_pad), dropout_rate)
            keep_scale = jnp.where(keep, 1.0 / (1.0 - dropout_rate), 0.0)
        do = do_ref[:, sl]
        p_dv = p * keep_scale if keep_scale is not None else p
        dv_acc[:, sl] += jax.lax.dot_general(
            p_dv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[:, sl], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep_scale is not None:
            dp = dp * keep_scale
        ds = p * (dp - delta_ref[which, :][:, None])
        dsc = ds.astype(q_ref.dtype)
        dq = (jax.lax.dot_general(
            dsc, k_ref[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        ).astype(dq_ref.dtype)
        dq_ref[pl.ds(qi * block_q, block_q), sl] = dq
        dk_acc[:, sl] += jax.lax.dot_general(
            dsc, q_ref[:, sl], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("heads", "d", "causal",
                                             "sm_scale", "block_q",
                                             "dropout_rate", "interpret"))
def _pair_bwd(qkv, o, lse, g, seed, heads, d, causal, sm_scale, block_q,
              dropout_rate=0.0, interpret=False):
    b, L, width = qkv.shape
    hpb = _heads_per_block(d)
    h2 = heads // hpb
    kv_pad = _round_up(L, 128)
    block_q = min(block_q, kv_pad)
    while kv_pad % block_q:
        block_q //= 2
    q_pad = kv_pad
    qkvp = _pad_len(qkv, kv_pad)
    gp = _pad_len(g, kv_pad)
    delta = jnp.sum((g.astype(jnp.float32) * o.astype(jnp.float32))
                    .reshape(b, L, heads, d), axis=-1)       # [B, L, H]
    delta = jnp.transpose(delta, (0, 2, 1)).reshape(b, h2, hpb, L)
    delta = _pad_len(delta, q_pad, axis=3)
    lsep = _pad_len(lse, q_pad, axis=3)

    # one kv_pad-tall output block per (b, h2) and per grad: dq rows land
    # via pl.ds as q blocks sweep (q_pad == kv_pad by the block_q rule
    # above), dk/dv at the final q step
    grid = (b, h2, q_pad // block_q)
    qs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, *_: (bb, i, hh))
    ks = pl.BlockSpec((None, kv_pad, hpb * d),
                      lambda bb, hh, i, *_: (bb, 0, h2 + hh))
    vs = pl.BlockSpec((None, kv_pad, hpb * d),
                      lambda bb, hh, i, *_: (bb, 0, 2 * h2 + hh))
    gs = pl.BlockSpec((None, block_q, hpb * d),
                      lambda bb, hh, i, *_: (bb, i, hh))
    ls = pl.BlockSpec((None, None, hpb, block_q),
                      lambda bb, hh, i, *_: (bb, hh, 0, i))
    gpart = pl.BlockSpec((None, kv_pad, hpb * d),
                         lambda bb, hh, i, *_: (bb, 0, hh))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_pair_bwd_kernel, sm_scale=sm_scale, causal=causal,
                          d=d, kv_len=L, block_q=block_q, kv_pad=kv_pad,
                          dropout_rate=dropout_rate, n_heads=heads,
                          n_q=q_pad // block_q, hpb=hpb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[qs, ks, vs, gs, ls, ls],
            out_specs=[gpart, gpart, gpart],
            scratch_shapes=[pltpu.VMEM((kv_pad, hpb * d), jnp.float32),
                            pltpu.VMEM((kv_pad, hpb * d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((b, kv_pad, heads * d), qkv.dtype)
                   for _ in range(3)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, qkvp, qkvp, qkvp, gp, lsep, delta)
    # d(qkv) column order [q | k | v]; the concat feeds qkv_proj's backward
    # matmul and fuses there
    return jnp.concatenate([dq[:, :L], dk[:, :L], dv[:, :L]], axis=-1)


# ------------------------------------------------------------------ custom_vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def flash_pair(qkv, seed, heads, d, causal, sm_scale, block_q, dropout_rate,
               interpret):
    out, _ = _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                       dropout_rate, interpret)
    return out


def _pair_vjp_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                  dropout_rate, interpret):
    out, lse = _pair_fwd(qkv, seed, heads, d, causal, sm_scale, block_q,
                         dropout_rate, interpret)
    return out, (qkv, out, lse, seed)


def _pair_vjp_bwd(heads, d, causal, sm_scale, block_q, dropout_rate,
                  interpret, res, g):
    qkv, out, lse, seed = res
    dqkv = _pair_bwd(qkv, out, lse, g, seed, heads, d, causal, sm_scale,
                     block_q, dropout_rate, interpret)
    return dqkv, None


flash_pair.defvjp(_pair_vjp_fwd, _pair_vjp_bwd)


def flash_pair_packed(qkv, num_heads, causal, dropout_rate=0.0, seed=0,
                      block_q=512, interpret=False):
    """Keyword front door for the pair path: derives head_dim/scale/seed form
    so call sites don't hand-assemble the 9-positional custom_vjp call."""
    d = qkv.shape[-1] // (3 * num_heads)
    if not pair_layout_supported(d, num_heads, qkv.shape[1]):
        # fail fast: a truncating heads // hpb would leave trailing heads'
        # output columns unwritten (silent NaN/garbage)
        raise ValueError(
            f"flash_pair: unsupported shape (head_dim={d}, "
            f"num_heads={num_heads}, L={qkv.shape[1]}); requires "
            f"num_heads % max(1, 128 // head_dim) == 0, hpb*d % 128 == 0, "
            f"and L <= 1024 — use flash_attention_blhd/packed instead")
    seed_arr = jnp.atleast_1d(jnp.asarray(seed, jnp.int32))
    return flash_pair(qkv, seed_arr, int(num_heads), int(d), bool(causal),
                      1.0 / math.sqrt(d), int(block_q), float(dropout_rate),
                      bool(interpret))
