"""Pallas TPU dropout: hardware-PRNG mask generation fused with apply.

Reference analog: phi/kernels/gpu/dropout_kernel.cu (curand mask + scale in one
kernel). The XLA path pays the counter-based threefry chain (~10 VPU ops per
element) plus separate compare/select passes — measured ~3 ms per [64,512,768]
dropout on a v5e, ~78 ms of a BERT-base train step. This kernel draws bits from
the TPU hardware PRNG (pltpu.prng_random_bits), so mask-gen + apply is ~2 VPU
passes. The backward regenerates the identical mask from the same seed — the
mask never exists in HBM in either direction.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dropout_kernel(seed_ref, x_ref, o_ref, *, rate, scale):
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], i)
    bits = pltpu.prng_random_bits(x_ref.shape)
    bits = jax.lax.bitwise_and(bits, jnp.int32(0x7FFFFFFF))
    threshold = jnp.int32(int(rate * 2147483648.0))
    keep = bits >= threshold
    o_ref[:] = jnp.where(keep, x_ref[:] * scale, 0.0).astype(o_ref.dtype)


def _row_block(rows, cols, itemsize):
    """Pick a row-tile so each block stays ~1MB (VMEM-friendly, few grid steps)."""
    target = max(1, (1 << 20) // max(1, cols * itemsize))
    block = 1
    while block * 2 <= target and block * 2 <= rows:
        block *= 2
    while rows % block:
        block //= 2
    return max(block, 1)


@functools.partial(jax.jit, static_argnames=("rate", "scale", "shape"))
def _dropout_2d(x2, seed, rate, scale, shape):
    rows, cols = x2.shape
    block = _row_block(rows, cols, x2.dtype.itemsize)
    out = pl.pallas_call(
        functools.partial(_dropout_kernel, rate=rate, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows // block,),
            in_specs=[pl.BlockSpec((block, cols), lambda i, *_: (i, 0))],
            out_specs=pl.BlockSpec((block, cols), lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x2.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(seed, x2)
    return out.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def dropout_tpu(x, seed, rate: float, upscale: bool = True):
    """dropout(x) with the mask drawn in-kernel from `seed` (int32 scalar
    array). Deterministic per seed: calling twice with the same seed gives the
    same mask — the backward relies on exactly this (the custom_vjp applies
    the identical kernel to the cotangent; the mask never exists in HBM)."""
    return _dropout_apply(x, seed, rate, upscale)


def _dropout_vjp_fwd(x, seed, rate, upscale):
    return _dropout_apply(x, seed, rate, upscale), seed


def _dropout_vjp_bwd(rate, upscale, seed, g):
    return _dropout_apply(g, seed, rate, upscale), None


dropout_tpu.defvjp(_dropout_vjp_fwd, _dropout_vjp_bwd)


def _dropout_apply(x, seed, rate: float, upscale: bool = True):
    shape = tuple(x.shape)
    n = 1
    for s in shape:
        n *= s
    cols = shape[-1] if len(shape) >= 2 else n
    if cols % 128 or (n // cols) < 1 or n % cols:
        # lane-quantum fallback: flatten to a 128-wide 2D form when possible
        cols = 128 if n % 128 == 0 else 0
    if cols == 0:
        raise ValueError(f"dropout_tpu needs size % 128 == 0, got shape {shape}")
    x2 = x.reshape(n // cols, cols)
    scale = (1.0 / (1.0 - rate)) if upscale else 1.0
    return _dropout_2d(x2, jnp.atleast_1d(jnp.asarray(seed, jnp.int32)),
                       float(rate), float(scale), shape)


def dropout_path_available(x) -> bool:
    """TPU placement + lane-quantum size check (no interpret lowering for
    the hardware PRNG)."""
    n = 1
    for s in x.shape:
        n *= s
    if n == 0 or n % 128:
        return False
    from .util import tpu_placement
    return tpu_placement(x)
