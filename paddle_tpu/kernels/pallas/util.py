"""Shared gates for the Pallas TPU kernel family."""
from __future__ import annotations

import jax


def tpu_placement(x) -> bool:
    """True when `x` will execute on a real TPU. Must NOT observe the value:
    under deferred eager a .value() here would flush the pending graph at
    every availability check. Concrete arrays answer from their devices;
    tracers and LazyArrays answer from where the program will run."""
    arr = getattr(x, "_data", x)
    if isinstance(arr, jax.Array) and not isinstance(arr, jax.core.Tracer):
        try:
            return any(d.platform == "tpu" for d in arr.devices())
        except Exception:
            pass
    return jax.default_backend() == "tpu"


def _install_compiler_params_alias():
    """jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; the
    kernels are written against the current name. On 0.4.x, alias it so the
    same kernel source drives both."""
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "CompilerParams") and \
            hasattr(pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


_install_compiler_params_alias()
