"""Shared gates for the Pallas TPU kernel family."""
from __future__ import annotations

import jax


def tpu_placement(x) -> bool:
    """True when `x` will execute on a real TPU. Must NOT observe the value:
    under deferred eager a .value() here would flush the pending graph at
    every availability check. Concrete arrays answer from their devices;
    tracers and LazyArrays answer from where the program will run."""
    arr = getattr(x, "_data", x)
    if isinstance(arr, jax.Array) and not isinstance(arr, jax.core.Tracer):
        try:
            return any(d.platform == "tpu" for d in arr.devices())
        except Exception:
            pass
    return jax.default_backend() == "tpu"
