"""Kernel autotune: measured config selection with a persistent cache.

Reference analog: phi/kernels/autotune/ — cache.h:76 keys algorithm choices by
op + shape signature, switch_autotune.cc turns measurement on/off, and the
gpu_timer measures candidate algorithms; the Python switch is
paddle.incubate.autotune.set_config.

TPU-native: the tunable knobs are Pallas grid/block parameters (a CUDA-algo
pick has no analog — XLA owns op lowering), so the cache maps
(kernel, shape-signature) -> block config. Candidates are measured on the real
device with compile excluded (warmup first), and results persist as JSON so a
job's first run pays the search once per shape family.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...core.flags import flag  # FLAGS_use_autotune / _cache_file live in core

__all__ = ["AutotuneCache", "autotune_pick", "enable", "disable", "status"]

_LOCK = threading.Lock()


class AutotuneCache:
    """(kernel, key) -> chosen config, persisted as JSON."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._mem: Dict[str, Any] = {}
        self._loaded = False
        self.hits = 0
        self.misses = 0

    def _ensure_loaded(self):
        if self._loaded:
            return
        self._loaded = True
        path = self._path or flag("FLAGS_autotune_cache_file")
        try:
            with open(path) as f:
                self._mem = json.load(f)
        except (OSError, ValueError):
            self._mem = {}

    @staticmethod
    def _k(kernel: str, key: Sequence) -> str:
        return kernel + "|" + ",".join(str(x) for x in key)

    def get(self, kernel: str, key: Sequence):
        with _LOCK:
            self._ensure_loaded()
            got = self._mem.get(self._k(kernel, key))
            if got is None:
                self.misses += 1
            else:
                self.hits += 1
            return got

    def put(self, kernel: str, key: Sequence, config):
        with _LOCK:
            self._ensure_loaded()
            self._mem[self._k(kernel, key)] = config
            path = self._path or flag("FLAGS_autotune_cache_file")
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(self._mem, f)
                os.replace(tmp, path)
            except OSError:
                pass  # cache is an optimization; never fail the op

    def clear(self):
        with _LOCK:
            self._mem = {}
            self._loaded = True


_CACHE = AutotuneCache()


def cache() -> AutotuneCache:
    return _CACHE


def autotune_pick(kernel: str, key: Sequence,
                  candidates: List[Tuple],
                  measure: Callable[[Tuple], Callable[[], Any]],
                  warmup: int = 1, iters: int = 3) -> Tuple:
    """Return the fastest candidate for (kernel, key), consulting the cache.

    `measure(config)` returns a zero-arg callable that runs the kernel to
    completion (caller blocks on the result); its first `warmup` calls are
    excluded (compile time). Failing candidates (e.g. VMEM overflow) are
    skipped. With a single candidate or autotune disabled the caller should
    not get here — this function always measures on a miss.
    """
    cached = _CACHE.get(kernel, key)
    if cached is not None:
        return tuple(cached)
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            fn = measure(cand)
            for _ in range(warmup):
                fn()
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue  # candidate doesn't lower / out of VMEM — skip
        if dt < best_t:
            best, best_t = cand, dt
    if best is None:
        raise RuntimeError(f"autotune: every candidate failed for {kernel} "
                           f"key={tuple(key)}")
    _CACHE.put(kernel, key, list(best))
    return best


def enable():
    from ...core.flags import set_flags
    set_flags({"FLAGS_use_autotune": True})


def disable():
    from ...core.flags import set_flags
    set_flags({"FLAGS_use_autotune": False})


def status() -> Dict[str, Any]:
    """reference autotune status (cache hit/miss counters)."""
    return {"use_autotune": flag("FLAGS_use_autotune"),
            "cache_hits": _CACHE.hits, "cache_misses": _CACHE.misses}
