"""Flagship model families for the benchmark configs (BASELINE.md).

The reference ships transformers in python/paddle/nn/layer/transformer.py and fused
variants in incubate; full LM architectures (GPT/BERT/ERNIE) live in PaddleNLP built on
those layers. Here they are first-class since they are the benchmark configs: GPT
(decoder LM, the north-star config) and BERT (encoder, the to_static config).
"""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM, gpt3_1p3b,  # noqa: F401
                  gpt_tiny, shard_gpt_tp)
from .bert import BertConfig, BertModel, BertForPreTraining, bert_base, bert_tiny  # noqa: F401
from .ernie import (ErnieConfig, ErnieModel,  # noqa: F401
                    ErnieForSequenceClassification, ErnieForMaskedLM,
                    ernie_tiny)
from .t5 import (T5Config, T5Model,  # noqa: F401
                 T5ForConditionalGeneration, t5_tiny)
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM, llama_tiny,  # noqa: F401
                    llama_7b, shard_llama_tp)
