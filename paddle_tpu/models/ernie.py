"""ERNIE encoder family (BASELINE.md config 5 names ERNIE-3.0).

ERNIE's architecture is the BERT post-LN encoder plus a task-type embedding
stream (multi-task pretraining); its signature knowledge-masking lives in the
DATA pipeline (entity/phrase spans), so the model side adds exactly the
task-embedding and the heads. Reference surface: ERNIE models live in
PaddleNLP built on python/paddle/nn (transformer.py) — here they are
first-class, reusing the paddle_tpu BERT blocks.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .bert import BertEmbeddings, BertLayer


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0          # 0 -> 4*hidden
    max_position_embeddings: int = 512
    type_vocab_size: int = 4            # sentence types (a/b + padding kinds)
    task_type_vocab_size: int = 16      # ERNIE's task-id embedding stream
    use_task_id: bool = True
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


def ernie_tiny(**overrides) -> "ErnieConfig":
    cfg = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=128)
    cfg.update(overrides)
    return ErnieConfig(**cfg)


class ErnieEmbeddings(nn.Layer):
    """BERT embeddings + the task-type stream (the ERNIE delta)."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.base = BertEmbeddings(config)
        self.task_type_embeddings = (
            nn.Embedding(config.task_type_vocab_size, config.hidden_size)
            if config.use_task_id else None)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None):
        if self.task_type_embeddings is None or task_type_ids is None:
            return self.base(input_ids, token_type_ids)
        # inject the task embedding before the shared LayerNorm/dropout:
        # recompute the sum the way BertEmbeddings does, plus the task term
        from .. import ops
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        emb = (self.base.word_embeddings(input_ids)
               + self.base.position_embeddings(pos)
               + self.task_type_embeddings(task_type_ids))
        if token_type_ids is not None:
            emb = emb + self.base.token_type_embeddings(token_type_ids)
        return self.base.dropout(self.base.layer_norm(emb))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config)
                                     for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)
        normal = nn.initializer.Normal(mean=0.0, std=config.initializer_range)
        for _, p in self.named_parameters():
            if p.ndim >= 2:
                p.set_value(normal(tuple(p.shape), p.dtype))

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids, task_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, task_type_ids,
                               attn_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return logits, F.cross_entropy(logits, labels)
        return logits


class ErnieForMaskedLM(nn.Layer):
    """Knowledge-masked LM head (tied decoder); the span masking itself is a
    data-pipeline concern — labels arrive with -100 on unmasked positions."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size,
                                           epsilon=config.layer_norm_epsilon)
        self.decoder_bias = self.create_parameter(
            shape=[config.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None, labels=None):
        seq_out, _ = self.ernie(input_ids, token_type_ids, task_type_ids,
                                attn_mask)
        x = self.transform_norm(F.gelu(self.transform(seq_out)))
        from .. import ops
        logits = ops.matmul(x, self.ernie.embeddings.base.word_embeddings.weight,
                            transpose_y=True) + self.decoder_bias
        if labels is not None:
            v = logits.shape[-1]
            return logits, F.cross_entropy(
                logits.reshape([-1, v]), labels.reshape([-1]),
                ignore_index=-100)
        return logits
