"""T5-style encoder-decoder LM — the seq2seq family of the model zoo.

Design notes (T5 recipe): RMS-style pre-norm (LayerNorm without bias/mean
subtraction), relative position biases shared across layers (bucketed,
bidirectional for the encoder, causal for the decoder), tied embedding, and
a gated-GELU feed-forward. Built on paddle_tpu.nn so it runs eager, traced,
and under mesh sharding like GPT/BERT/LLaMA (reference surface:
nn.Transformer in python/paddle/nn/layer/transformer.py:257 — full seq2seq
architectures live in PaddleNLP; here they are first-class zoo members).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from .. import ops


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6            # encoder depth == decoder depth
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    initializer_factor: float = 1.0


def t5_tiny(**overrides) -> "T5Config":
    cfg = dict(vocab_size=512, d_model=64, d_kv=16, d_ff=128, num_layers=2,
               num_heads=4)
    cfg.update(overrides)
    return T5Config(**cfg)


def _relative_bucket(rel_pos, bidirectional, num_buckets, max_distance):
    """T5's log-bucketed relative positions (numpy; built once per config)."""
    ret = np.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(np.int64) * num_buckets
        n = np.abs(n)
    else:
        n = np.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        np.log(np.maximum(n, 1) / max_exact)
        / math.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, num_buckets - 1)
    return ret + np.where(is_small, n, large)


class T5LayerNorm(nn.Layer):
    """RMS norm, no bias, no mean subtraction (the T5 variant)."""

    def __init__(self, d, eps):
        super().__init__()
        self.weight = self.create_parameter(
            [d], default_initializer=nn.initializer.Constant(1.0))
        self.eps = eps

    def forward(self, x):
        var = (x * x).mean(-1, keepdim=True)
        return x * ops.rsqrt(var + self.eps) * self.weight


class T5Attention(nn.Layer):
    def __init__(self, config: T5Config, has_relative_bias: bool,
                 bidirectional: bool):
        super().__init__()
        inner = config.num_heads * config.d_kv
        self.q = nn.Linear(config.d_model, inner, bias_attr=False)
        self.k = nn.Linear(config.d_model, inner, bias_attr=False)
        self.v = nn.Linear(config.d_model, inner, bias_attr=False)
        self.o = nn.Linear(inner, config.d_model, bias_attr=False)
        self.n_heads = config.num_heads
        self.d_kv = config.d_kv
        self.dropout = config.dropout_rate
        self._bias_cfg = (config.relative_attention_num_buckets,
                          config.relative_attention_max_distance,
                          bidirectional)
        self.relative_attention_bias = (
            nn.Embedding(config.relative_attention_num_buckets,
                         config.num_heads) if has_relative_bias else None)

    def _position_bias(self, q_len, kv_len):
        buckets, maxd, bidir = self._bias_cfg
        ctx = np.arange(q_len)[:, None]
        mem = np.arange(kv_len)[None, :]
        idx = _relative_bucket(mem - ctx, bidir, buckets, maxd)
        from ..core.tensor import Tensor
        bias = self.relative_attention_bias(Tensor(idx.astype(np.int64)))
        return bias.transpose([2, 0, 1]).unsqueeze(0)   # [1, H, Lq, Lk]

    def forward(self, x, kv=None, attn_mask=None, position_bias=None,
                causal=False):
        b, lq, _ = x.shape
        src = kv if kv is not None else x
        lk = src.shape[1]
        q = self.q(x).reshape([b, lq, self.n_heads, self.d_kv])
        k = self.k(src).reshape([b, lk, self.n_heads, self.d_kv])
        v = self.v(src).reshape([b, lk, self.n_heads, self.d_kv])
        if position_bias is None and self.relative_attention_bias is not None:
            position_bias = self._position_bias(lq, lk)
        mask = attn_mask
        if position_bias is not None:
            mask = position_bias if mask is None else mask + position_bias
        # T5 scales by 1.0 (folded into init), so undo sdpa's 1/sqrt(d)
        out = F.scaled_dot_product_attention(
            q * math.sqrt(self.d_kv), k, v, attn_mask=mask,
            dropout_p=self.dropout if self.training else 0.0,
            is_causal=causal, training=self.training)
        return self.o(out.reshape([b, lq, self.n_heads * self.d_kv])), \
            position_bias


class T5FF(nn.Layer):
    """Gated-GELU feed-forward (T5 v1.1 recipe)."""

    def __init__(self, config: T5Config):
        super().__init__()
        self.wi_0 = nn.Linear(config.d_model, config.d_ff, bias_attr=False)
        self.wi_1 = nn.Linear(config.d_model, config.d_ff, bias_attr=False)
        self.wo = nn.Linear(config.d_ff, config.d_model, bias_attr=False)

    def forward(self, x):
        return self.wo(F.gelu(self.wi_0(x), approximate=True) * self.wi_1(x))


class T5Block(nn.Layer):
    def __init__(self, config: T5Config, is_decoder: bool,
                 has_relative_bias: bool):
        super().__init__()
        self.is_decoder = is_decoder
        eps = config.layer_norm_epsilon
        self.self_norm = T5LayerNorm(config.d_model, eps)
        self.self_attn = T5Attention(config, has_relative_bias,
                                     bidirectional=not is_decoder)
        if is_decoder:
            self.cross_norm = T5LayerNorm(config.d_model, eps)
            self.cross_attn = T5Attention(config, False, bidirectional=True)
        self.ff_norm = T5LayerNorm(config.d_model, eps)
        self.ff = T5FF(config)
        self.drop = nn.Dropout(config.dropout_rate)

    def forward(self, x, enc=None, position_bias=None, self_mask=None,
                cross_mask=None):
        a, position_bias = self.self_attn(self.self_norm(x), attn_mask=self_mask,
                                          position_bias=position_bias,
                                          causal=self.is_decoder)
        x = x + self.drop(a)
        if self.is_decoder:
            # cross-attention masks the SOURCE pads (T5 semantics: the encoder
            # attention_mask applies to cross-attention too)
            c, _ = self.cross_attn(self.cross_norm(x), kv=enc,
                                   attn_mask=cross_mask)
            x = x + self.drop(c)
        x = x + self.drop(self.ff(self.ff_norm(x)))
        return x, position_bias


class T5Stack(nn.Layer):
    def __init__(self, config: T5Config, is_decoder: bool, embed):
        super().__init__()
        self.embed = embed
        self.is_decoder = is_decoder
        # relative bias lives in the FIRST layer, shared by the rest (T5)
        self.blocks = nn.LayerList(
            [T5Block(config, is_decoder, has_relative_bias=(i == 0))
             for i in range(config.num_layers)])
        self.final_norm = T5LayerNorm(config.d_model,
                                      config.layer_norm_epsilon)
        self.drop = nn.Dropout(config.dropout_rate)

    def forward(self, ids, enc=None, self_mask=None, cross_mask=None):
        x = self.drop(self.embed(ids))
        bias = None
        for blk in self.blocks:
            x, bias = blk(x, enc=enc, position_bias=bias, self_mask=self_mask,
                          cross_mask=cross_mask)
        return self.drop(self.final_norm(x))


class T5Model(nn.Layer):
    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        self.shared = nn.Embedding(config.vocab_size, config.d_model)
        self.encoder = T5Stack(config, is_decoder=False, embed=self.shared)
        self.decoder = T5Stack(config, is_decoder=True, embed=self.shared)
        normal = nn.initializer.Normal(
            mean=0.0, std=config.initializer_factor / math.sqrt(config.d_model))
        for name, p in self.named_parameters():
            if p.ndim >= 2:
                p.set_value(normal(tuple(p.shape), p.dtype))

    def forward(self, input_ids, decoder_input_ids, enc_mask=None):
        enc = self.encoder(input_ids, self_mask=enc_mask)
        return self.decoder(decoder_input_ids, enc=enc, cross_mask=enc_mask)


class T5ForConditionalGeneration(nn.Layer):
    def __init__(self, config: T5Config):
        super().__init__()
        self.t5 = T5Model(config)
        self.config = config

    def _head(self, hidden):
        # tied head, T5's rescaling by d_model^-0.5
        return ops.matmul(hidden * (self.config.d_model ** -0.5),
                          self.t5.shared.weight, transpose_y=True)

    def forward(self, input_ids, decoder_input_ids, labels=None,
                enc_mask=None):
        hidden = self.t5(input_ids, decoder_input_ids, enc_mask)
        logits = self._head(hidden)
        if labels is not None:
            v = logits.shape[-1]
            loss = F.cross_entropy(logits.reshape([-1, v]),
                                   labels.reshape([-1]), ignore_index=-100)
            return logits, loss
        return logits

    def greedy_generate(self, input_ids, max_len=16, bos_id=0, eos_id=1,
                        enc_mask=None):
        """Minimal greedy decode: the source is encoded ONCE; the decoder
        re-runs its full prefix per step (serving-grade KV-cache decoding
        lives in the inference engine)."""
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor
        b = input_ids.shape[0]
        dec = np.full((b, 1), bos_id, np.int64)
        with no_grad():
            enc = self.t5.encoder(input_ids, self_mask=enc_mask)
            for _ in range(max_len - 1):
                hidden = self.t5.decoder(Tensor(dec), enc=enc,
                                         cross_mask=enc_mask)
                logits = self._head(hidden)
                nxt = np.asarray(logits.value())[:, -1].argmax(-1)
                dec = np.concatenate([dec, nxt[:, None].astype(np.int64)], 1)
                if (nxt == eos_id).all():
                    break
        return dec
