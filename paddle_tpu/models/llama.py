"""LLaMA decoder family — RoPE + RMSNorm + SwiGLU + grouped-query attention.

SURVEY.md §6 stretch target (LLaMA-7B TP+PP). Built on the same substrate as
GPT: paddle_tpu.nn layers for eager/tape, the Pallas flash kernel where
eligible, the fused lm_head_ce loss, and TP via NamedSharding re-placement of
the q/k/v/o and gate/up/down projections (shard_llama_tp below).

Reference analogs for the building blocks: nn.RMSNorm surface
(python/paddle/nn — added post-snapshot upstream; here a first-class layer),
fused rotary embedding (incubate fused ops family).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from .. import ops
from ..core.remat import (ATTN_CONTEXT, ATTN_OUT, ATTN_QKV, MLP_HIDDEN,
                          normalize_granularity, tag_activation)
from ..ops._helpers import _op

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny",
           "llama_7b", "shard_llama_tp"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0          # 0 -> = num_heads (MHA); < heads -> GQA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    initializer_range: float = 0.02
    # activation recompute ("none" | "selective" | "dots" | "full");
    # interval=N checkpoints every Nth block — see fleet/recompute.py
    recompute_granularity: str = "none"
    recompute_interval: int = 1

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads
        self.recompute_granularity, self.recompute_interval = \
            normalize_granularity(self.recompute_granularity,
                                  self.recompute_interval)


def llama_7b(**overrides) -> LlamaConfig:
    cfg = dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
               num_layers=32, num_heads=32)
    cfg.update(overrides)
    return LlamaConfig(**cfg)


def llama_tiny(**overrides) -> LlamaConfig:
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_layers=2, num_heads=4, num_kv_heads=2,
               max_position_embeddings=128)
    cfg.update(overrides)
    return LlamaConfig(**cfg)


def _rope_fwd(q, k, *rest, theta=10000.0, has_pos=False):
    """Rotary embedding applied to q,k [B,S,H,D] (interleaved-pair form).
    Optional trailing position offset (KV-cache decoding: the chunk starts
    at an absolute position, not 0) — a scalar (lockstep batch) or a [B]
    vector (serving slots, each row at its own depth)."""
    B, S, H, D = q.shape
    p0 = rest[0].astype(jnp.float32) if has_pos else jnp.float32(0.0)
    # [S] for a scalar offset, [B, S] for per-row offsets
    pos = jnp.asarray(p0)[..., None] + jnp.arange(S, dtype=jnp.float32)
    inv = theta ** (-jnp.arange(0, D, 2, dtype=jnp.float32) / D)
    ang = pos[..., None] * inv                 # [S, D/2] or [B, S, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    if ang.ndim == 2:
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., ::2], x[..., 1::2]
        xr1 = x1 * cos - x2 * sin
        xr2 = x1 * sin + x2 * cos
        return jnp.stack([xr1, xr2], axis=-1).reshape(x.shape)

    return rot(q.astype(jnp.float32)).astype(q.dtype), \
        rot(k.astype(jnp.float32)).astype(k.dtype)


from ..core.dispatch import register_op  # noqa: E402

register_op("rope", _rope_fwd)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        H = config.hidden_size
        self.num_heads = config.num_heads
        self.num_kv = config.num_kv_heads
        self.head_dim = H // config.num_heads
        self.theta = config.rope_theta
        self.use_flash = config.use_flash_attention
        self.q_proj = nn.Linear(H, H, bias_attr=False)
        self.k_proj = nn.Linear(H, self.num_kv * self.head_dim,
                                bias_attr=False)
        self.v_proj = nn.Linear(H, self.num_kv * self.head_dim,
                                bias_attr=False)
        self.o_proj = nn.Linear(H, H, bias_attr=False)

    def forward(self, x, kv_cache=None):
        if kv_cache is not None:
            return self._forward_cached(x, kv_cache)
        b, s, h = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv, self.head_dim])
        v = tag_activation(
            self.v_proj(x), ATTN_QKV).reshape([b, s, self.num_kv,
                                               self.head_dim])
        q, k = _op("rope", q, k, theta=self.theta)
        # selective recompute saves the POST-rope q/k (so backward replays
        # neither the projections nor the rotation) and raw v
        q = tag_activation(q, ATTN_QKV)
        k = tag_activation(k, ATTN_QKV)
        # GQA is handled below the functional API: the Pallas kernel folds q
        # heads onto their KV head in its index map (repeated K/V never
        # materializes in HBM); the XLA sdpa fallback expands heads itself
        from ..nn.functional.attention import flash_path_available
        if self.use_flash and flash_path_available(s, self.head_dim, x):
            out = F.flash_attention(q, k, v, causal=True,
                                    training=self.training)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                 training=self.training)
        # name the context like GPT does: selective remat then saves it (the
        # score/softmax region stays the part recomputed in backward) and
        # the health plane gets its per-layer context RMS
        out = tag_activation(out, ATTN_CONTEXT)
        return tag_activation(self.o_proj(out.reshape([b, s, h])), ATTN_OUT)

    def _forward_cached(self, x, kv_cache):
        """KV-cache attention with RoPE at absolute positions and GQA
        (queries fold onto their KV head). Inference-only raw-array math —
        mirrors GPTAttention._forward_cached, including the paged layout
        (``(pool_k, pool_v, table, pos, write_end)``: block-pooled K/V read
        through the table via jnp.take; see gpt._paged_kv_update)."""
        from ..core.tensor import Tensor
        from .gpt import _paged_kv_update

        b, s, h = x.shape
        nh, nkv, hd = self.num_heads, self.num_kv, self.head_dim
        pos = kv_cache[3] if len(kv_cache) == 5 else kv_cache[2]
        q = self.q_proj(x).reshape([b, s, nh, hd])
        k = self.k_proj(x).reshape([b, s, nkv, hd])
        v = self.v_proj(x).reshape([b, s, nkv, hd])
        q, k = _op("rope", q, k, Tensor(jnp.asarray(pos)), theta=self.theta,
                   has_pos=True)
        qv, kv_, vv = q.value(), k.value(), v.value()
        if len(kv_cache) == 5:
            k_buf, v_buf, new_cache = _paged_kv_update(kv_cache, kv_, vv)
        else:
            k_buf, v_buf, _ = kv_cache      # [B, M, n_kv, hd] + cursor
            if jnp.ndim(pos) == 1:
                # per-slot cursors (serving engine): vmapped per-row writes
                upd = lambda buf, kv, p: jax.lax.dynamic_update_slice(
                    buf, kv, (p, 0, 0))
                k_buf = jax.vmap(upd)(k_buf, kv_.astype(k_buf.dtype), pos)
                v_buf = jax.vmap(upd)(v_buf, vv.astype(v_buf.dtype), pos)
            else:
                k_buf = jax.lax.dynamic_update_slice(
                    k_buf, kv_.astype(k_buf.dtype), (0, pos, 0, 0))
                v_buf = jax.lax.dynamic_update_slice(
                    v_buf, vv.astype(v_buf.dtype), (0, pos, 0, 0))
            new_cache = (k_buf, v_buf)
        if jnp.ndim(pos) == 1:
            q_pos = (pos[:, None] + jnp.arange(s))[:, None, None, :, None]
        else:
            q_pos = (pos + jnp.arange(s))[None, None, None, :, None]
        m = k_buf.shape[1]
        group = nh // nkv
        qg = qv.reshape(b, s, nkv, group, hd)
        scores = jnp.einsum("bqkgd,bmkd->bkgqm", qg.astype(jnp.float32),
                            k_buf.astype(jnp.float32)) / math.sqrt(hd)
        key_pos = jnp.arange(m)[None, None, None, None, :]
        scores = jnp.where(key_pos <= q_pos, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgqm,bmkd->bqkgd", probs,
                         v_buf.astype(jnp.float32)).astype(qv.dtype)
        out = self.o_proj(Tensor(ctx.reshape(b, s, h)))
        return out, new_cache


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        H, I = config.hidden_size, config.intermediate_size
        self.gate_proj = nn.Linear(H, I, bias_attr=False)
        self.up_proj = nn.Linear(H, I, bias_attr=False)
        self.down_proj = nn.Linear(I, H, bias_attr=False)

    def forward(self, x):
        return self.down_proj(
            F.silu(tag_activation(self.gate_proj(x), MLP_HIDDEN))
            * tag_activation(self.up_proj(x), MLP_HIDDEN))


class LlamaBlock(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, kv_cache=None):
        if kv_cache is not None:
            a, nc = self.self_attn(self.input_layernorm(x), kv_cache=kv_cache)
            x = x + a
            return x + self.mlp(self.post_attention_layernorm(x)), nc
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaBlock(config)
                                    for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)
        self._init_weights(config)

    def _init_weights(self, config):
        std = config.initializer_range
        normal = nn.initializer.Normal(mean=0.0, std=std)
        resid = nn.initializer.Normal(
            mean=0.0, std=std / math.sqrt(2.0 * config.num_layers))
        for name, p in self.named_parameters():
            if p.ndim >= 2:
                init = (resid if name.endswith(("o_proj.weight",
                                                "down_proj.weight"))
                        else normal)
                p.set_value(init(tuple(p.shape), p.dtype))

    def forward(self, input_ids, kv_caches=None, start_pos=None,
                write_end=None, layer_subset=None):
        """``layer_subset`` (non-cached path only): run just the named
        block indices — the early-exit speculative drafter's shallow pass
        over the same weights (see GPTModel.forward)."""
        x = self.embed_tokens(input_ids)
        if kv_caches is not None:
            p0 = start_pos if start_pos is not None else jnp.int32(0)
            we = write_end if write_end is not None else p0 + \
                jnp.int32(input_ids.shape[1])
            new_caches = []
            for block, cache in zip(self.layers, kv_caches):
                if len(cache) == 3:    # paged: (pool_k, pool_v, block_table)
                    kc = (cache[0], cache[1], cache[2], p0, we)
                else:                  # contiguous: (k_buf, v_buf)
                    kc = (cache[0], cache[1], p0)
                x, nc = block(x, kv_cache=kc)
                new_caches.append(nc)
            return self.norm(x), new_caches
        gran = self.config.recompute_granularity
        interval = self.config.recompute_interval
        from ..core import dispatch
        use_rc = (gran != "none" and self.training
                  and (dispatch.in_trace() or dispatch.is_grad_enabled()))
        for i, block in enumerate(self.layers):
            if layer_subset is not None and i not in layer_subset:
                continue
            if use_rc and i % interval == 0:
                from ..distributed.fleet.recompute import recompute
                x = recompute(block, x, policy=gran)
            else:
                x = block(x)
        return self.norm(x)

    def enable_recompute(self, granularity="selective", interval: int = 1):
        """Activation recompute toggle — see GPTModel.enable_recompute."""
        self.config.recompute_granularity, self.config.recompute_interval = \
            normalize_granularity(granularity, interval)
        return self

    @property
    def _recompute_wanted(self) -> bool:
        return self.config.recompute_granularity != "none"


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            # the head lives outside LlamaModel._init_weights' walk — apply
            # the same Normal(initializer_range) scheme here
            normal = nn.initializer.Normal(mean=0.0,
                                           std=config.initializer_range)
            self.lm_head.weight.set_value(
                normal(tuple(self.lm_head.weight.shape),
                       self.lm_head.weight.dtype))

    def enable_recompute(self, granularity="selective", interval: int = 1):
        """See LlamaModel.enable_recompute."""
        self.model.enable_recompute(granularity, interval)
        return self

    @property
    def _recompute_wanted(self) -> bool:
        return self.model._recompute_wanted

    def forward(self, input_ids, labels=None):
        hidden = self.model(input_ids)
        if labels is not None:
            tied = self.lm_head is None
            w = self.model.embed_tokens.weight if tied else self.lm_head.weight
            loss = _op("lm_head_ce", hidden[:, :-1, :], w, labels[:, 1:],
                       transpose_w=tied)
            return None, loss
        if self.lm_head is None:
            return ops.matmul(hidden, self.model.embed_tokens.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, do_sample: bool = False,
                 top_k: int = 0, eos_token_id=None, seed=None,
                 max_length=None, use_engine: bool = False):
        """KV-cache incremental decoding — same compiled prefill+decode
        machinery as GPTForCausalLM.generate (RoPE positions offset by the
        cache cursor, GQA K/V buffers sized [B, M, n_kv, hd]); ``seed=None``
        derives sampling randomness from ``paddle.seed`` via
        ``core.random.host_generator()``. ``use_engine=True`` routes through
        the serving DecodeEngine (paged cache + slot scheduler)."""
        from .gpt import _generate_with_cache
        cfg = self.config
        if use_engine:
            from ..serving import generate_via_engine
            return generate_via_engine(
                self, input_ids, max_new_tokens=max_new_tokens,
                temperature=temperature, do_sample=do_sample, top_k=top_k,
                eos_token_id=eos_token_id, seed=seed, max_length=max_length)
        return _generate_with_cache(
            self, self.model, cfg.num_layers, cfg.num_kv_heads,
            cfg.hidden_size // cfg.num_heads,
            cfg.max_position_embeddings,
            head_weight=(self.model.embed_tokens.weight
                         if self.lm_head is None else self.lm_head.weight),
            head_transpose=self.lm_head is None,
            input_ids=input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, do_sample=do_sample, top_k=top_k,
            eos_token_id=eos_token_id, seed=seed, max_length=max_length)


def shard_llama_tp(model: LlamaForCausalLM, mesh=None, axis: str = "model"):
    """Tensor-parallel placement: column-shard q/k/v/gate/up, row-shard
    o/down, vocab-shard the embedding (the Fleet mp_layers recipe as
    NamedShardings — XLA inserts the TP collectives).

    Serving: a model sharded here makes ``serving.DecodeEngine`` mint SPMD
    executables with the paged KV pools head-sharded over ``axis``; when
    the GQA head count doesn't divide the TP degree (``num_kv_heads % tp
    != 0``) the engine falls back to sharding head_dim, so grouped-query
    models still scale past their KV-head count (gated at TP=4 with
    num_kv_heads=2 in tests/test_tp_serving.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..distributed.env import get_mesh
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return model
    col = NamedSharding(mesh, P(None, axis))
    row = NamedSharding(mesh, P(axis, None))
    for name, p in model.named_parameters():
        if name.endswith(("q_proj.weight", "k_proj.weight", "v_proj.weight",
                          "gate_proj.weight", "up_proj.weight")):
            p._data = jax.device_put(p.value(), col)
        elif name.endswith(("o_proj.weight", "down_proj.weight")):
            p._data = jax.device_put(p.value(), row)
        elif name.endswith("embed_tokens.weight"):
            p._data = jax.device_put(p.value(), row)
        elif name.endswith("lm_head.weight"):
            p._data = jax.device_put(p.value(), col)
    return model
