"""BERT encoder LM — the to_static benchmark config (BASELINE.md config 2).

Post-LN transformer encoder per the original BERT recipe, with MLM + NSP pretraining
heads. Built on paddle_tpu.nn (reference surface: nn.TransformerEncoder,
/root/reference/python/paddle/nn/layer/transformer.py:137 — full architectures live in
PaddleNLP; here they are first-class benchmark models).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .. import ops


@dataclass
class BertConfig:
    vocab_size: int = 30528            # 30522 padded to a multiple of 64
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02


def bert_base(**overrides) -> "BertConfig":
    cfg = dict()
    cfg.update(overrides)
    return BertConfig(**cfg)


def bert_tiny(**overrides) -> "BertConfig":
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               intermediate_size=128, max_position_embeddings=128)
    cfg.update(overrides)
    return BertConfig(**cfg)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings,
                                                config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertLayer(nn.Layer):
    """Post-LN encoder block (attention → add&norm → FFN → add&norm)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.attn_norm = nn.LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_epsilon)
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)
        self.ffn_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.attn_dropout_p = config.attention_dropout_prob

    def forward(self, x, attn_mask=None, seq_lens=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        if attn_mask is None and seq_lens is None:
            # packed path: attention reads the projection output in place
            # (head-pair kernels at head_dim 64 — no [B,L,H,D] relayouts)
            attn = F.flash_attention_qkv_packed(
                qkv, self.num_heads, causal=False,
                dropout=self.attn_dropout_p, training=self.training)
        else:
            qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
            q, k, v = qkv.unbind(2)
            attn = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, kv_lens=seq_lens,
                dropout_p=self.attn_dropout_p if self.training else 0.0)
            attn = attn.reshape([b, s, h])
        attn = self.out_proj(attn)
        # fused residual epilogue: LayerNorm(x + dropout(sub)) in one Pallas
        # pass on TPU (F.add_dropout_ln; unfused composition elsewhere) —
        # the reference's fused_attention/fused_feedforward epilogue analog
        x = F.add_dropout_ln(x, attn, self.attn_norm.weight,
                             self.attn_norm.bias, p=self.dropout.p,
                             epsilon=self.attn_norm._epsilon,
                             training=self.training)
        # tanh-approximate gelu: |tanh-form - erf-form| <= ~1e-3, below
        # bf16 activation rounding (~8e-3 relative) — and the erf
        # polynomial costs ~2x the VPU ops (measured 16 ms/step at
        # BERT-base B=64); reference nn.GELU(approximate=True) parity
        ffn = self.fc_out(F.gelu(self.fc_in(x), approximate=True))
        return F.add_dropout_ln(x, ffn, self.ffn_norm.weight,
                                self.ffn_norm.bias, p=self.dropout.p,
                                epsilon=self.ffn_norm._epsilon,
                                training=self.training)


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config)
                                     for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)
        self._init_weights(config)

    def _init_weights(self, config):
        normal = nn.initializer.Normal(mean=0.0, std=config.initializer_range)
        for _, p in self.named_parameters():
            if p.ndim >= 2:
                p.set_value(normal(tuple(p.shape), p.dtype))

    def forward(self, input_ids, token_type_ids=None, attn_mask=None,
                seq_lens=None):
        # seq_lens ([B] int): per-sequence valid-token counts — the structured
        # (Pallas-flash) form of the usual [B,1,1,L] padding attn_mask
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask, seq_lens)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPreTraining(nn.Layer):
    """MLM (tied decoder) + NSP heads; forward returns (mlm_logits, nsp_logits) or the
    summed pretraining loss when labels are given."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size,
                                           epsilon=config.layer_norm_epsilon)
        self.decoder_bias = self.create_parameter(
            shape=[config.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None,
                masked_lm_labels=None, next_sentence_labels=None,
                seq_lens=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids, attn_mask,
                                    seq_lens)
        x = self.transform_norm(F.gelu(self.transform(seq_out),
                                       approximate=True))
        nsp_logits = self.nsp_head(pooled)
        if masked_lm_labels is None:
            mlm_logits = ops.matmul(
                x, self.bert.embeddings.word_embeddings.weight,
                transpose_y=True) + self.decoder_bias
            return mlm_logits, nsp_logits
        # fused head+CE (gpt.py lm_head_ce): the [B,S,V] fp32 logits never
        # materialize on the loss path — at BERT-base that's a 2GB tensor
        from ..ops._helpers import _op
        mlm_loss = _op("lm_head_ce", x, self.bert.embeddings.word_embeddings
                       .weight, masked_lm_labels, self.decoder_bias,
                       transpose_w=True, has_bias=True)
        loss = mlm_loss
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          next_sentence_labels.reshape([-1]))
        return loss
