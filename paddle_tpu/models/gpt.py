"""GPT decoder-only LM — the flagship / north-star model (BASELINE.md config 4).

Architecture follows the GPT-3 recipe (pre-LN transformer decoder, learned position
embeddings, GELU MLP with 4x width, tied LM head). Built on paddle_tpu.nn layers so the
same module runs eager, under @to_static, and under mesh sharding (the distributed
wrappers re-place parameter arrays with NamedShardings; see
paddle_tpu/distributed/fleet/meta_parallel).

Reference analogs: nn.TransformerDecoderLayer surface
(/root/reference/python/paddle/nn/layer/transformer.py) and the fused incubate stack
(/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py:1021
FusedMultiTransformer) — here fusion is XLA's job, and attention uses
F.scaled_dot_product_attention (Pallas flash path on real TPUs).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from .. import ops
from ..core.dispatch import register_op
from ..ops._helpers import _op


def _lm_head_ce_fwd(hidden, weight, labels, transpose_w=True, ignore_index=-100):
    """Fused LM-head + next-token CE: hidden [B,S,H] (pre-shifted), weight
    [V,H] (tied embedding) or [H,V], labels [B,S] → scalar mean loss over
    non-ignored tokens.

    One executable computes matmul → logsumexp → label-gather; the [B,S,V]
    logits never round-trip HBM in fp32 and no log-softmax tensor is formed
    (reference c_softmax_with_cross_entropy plays the same fusion role for the
    vocab-parallel case)."""
    dims = (((2,), (1,)), ((), ())) if transpose_w else (((2,), (0,)), ((), ()))
    logits = jax.lax.dot_general(hidden, weight, dims,
                                 preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    valid = lbl != ignore_index
    gold = jnp.take_along_axis(
        logits, jnp.where(valid, lbl, 0)[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, lse - gold, 0.0)
    n = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return jnp.sum(per_tok) / n


register_op("lm_head_ce", _lm_head_ce_fwd, nondiff_inputs=(2,))


@dataclass
class GPTConfig:
    vocab_size: int = 50304            # 50257 padded to a multiple of 128 for the MXU
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 2048
    intermediate_size: int = 0         # 0 → 4*hidden
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


def gpt3_1p3b(**overrides) -> "GPTConfig":
    """GPT-3 XL, 1.3B params: 24 layers, d=2048, 16 heads (BASELINE north star)."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
               max_position_embeddings=2048)
    cfg.update(overrides)
    return GPTConfig(**cfg)


def gpt_tiny(**overrides) -> "GPTConfig":
    """Tiny config for tests / dryruns."""
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=128)
    cfg.update(overrides)
    return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout_p = config.attention_dropout_prob
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)          # each [b, s, heads, head_dim]
        drop = self.dropout_p if self.training else 0.0
        if self.use_flash and attn_mask is None:
            # Pallas flash kernel on real TPUs (auto-detected, in-kernel
            # dropout); XLA sdpa otherwise
            out = F.flash_attention(q, k, v, dropout=drop, causal=True,
                                    training=self.training)
        else:
            # always causal; attn_mask (e.g. additive padding mask) combines with it
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=drop, training=self.training,
                is_causal=True)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(nn.Layer):
    """Pre-LN decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = x + self.dropout(self.attn(self.ln_1(x), attn_mask))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self._init_weights(config)

    def _init_weights(self, config):
        std = config.initializer_range
        normal = nn.initializer.Normal(mean=0.0, std=std)
        resid_scale = nn.initializer.Normal(
            mean=0.0, std=std / math.sqrt(2.0 * config.num_layers))
        for name, p in self.named_parameters():
            if p.ndim >= 2:
                # GPT-2/3 init: residual-out projections scaled by 1/sqrt(2L)
                init = (resid_scale if name.endswith(("out_proj.weight",
                                                      "fc_out.weight")) else normal)
                p.set_value(init(tuple(p.shape), p.dtype))

    def forward(self, input_ids, attn_mask=None):
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head on GPTModel; loss = shifted next-token cross-entropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # reuse wte
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.gpt(input_ids, attn_mask)
        if labels is not None:
            # loss from the SHIFTED hidden states: the slice happens on [B,S,H]
            # (not [B,S,V]) and the head matmul + CE fuse into one executable;
            # the full-logits below are dead code under jit when only the loss
            # is consumed (XLA DCE removes the second head matmul)
            tied = self.lm_head is None
            w = self.gpt.wte.weight if tied else self.lm_head.weight
            loss = _op("lm_head_ce", hidden[:, :-1, :], w, labels[:, 1:],
                       transpose_w=tied)
        if self.lm_head is None:
            logits = ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        return logits, loss
