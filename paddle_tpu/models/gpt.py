"""GPT decoder-only LM — the flagship / north-star model (BASELINE.md config 4).

Architecture follows the GPT-3 recipe (pre-LN transformer decoder, learned position
embeddings, GELU MLP with 4x width, tied LM head). Built on paddle_tpu.nn layers so the
same module runs eager, under @to_static, and under mesh sharding (the distributed
wrappers re-place parameter arrays with NamedShardings; see
paddle_tpu/distributed/fleet/meta_parallel).

Reference analogs: nn.TransformerDecoderLayer surface
(/root/reference/python/paddle/nn/layer/transformer.py) and the fused incubate stack
(/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py:1021
FusedMultiTransformer) — here fusion is XLA's job, and attention uses
F.scaled_dot_product_attention (Pallas flash path on real TPUs).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from .. import ops
from ..core.dispatch import register_op
from ..core.remat import (ATTN_CONTEXT, ATTN_OUT, ATTN_QKV, MLP_HIDDEN,
                          normalize_granularity, note_region, resolve_policy,
                          tag_activation, tag_array)
from ..core.tensor import Tensor
from ..ops._helpers import _op


def _lm_head_ce_fwd(hidden, weight, labels, *rest, transpose_w=True,
                    ignore_index=-100, has_bias=False):
    """Fused LM-head + next-token CE: hidden [B,S,H] (pre-shifted), weight
    [V,H] (tied embedding) or [H,V], labels [B,S] → scalar mean loss over
    non-ignored tokens. Optional trailing bias [V] (BERT's MLM decoder).

    One executable computes matmul → logsumexp → label-gather; the [B,S,V]
    logits never round-trip HBM in fp32 and no log-softmax tensor is formed
    (reference c_softmax_with_cross_entropy plays the same fusion role for the
    vocab-parallel case)."""
    dims = (((2,), (1,)), ((), ())) if transpose_w else (((2,), (0,)), ((), ()))
    logits = jax.lax.dot_general(hidden, weight, dims,
                                 preferred_element_type=jnp.float32)
    if has_bias:
        logits = logits + rest[0].astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    valid = lbl != ignore_index
    gold = jnp.take_along_axis(
        logits, jnp.where(valid, lbl, 0)[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, lse - gold, 0.0)
    n = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return jnp.sum(per_tok) / n


register_op("lm_head_ce", _lm_head_ce_fwd, nondiff_inputs=(2,))


def _gpt_scan_blocks_fwd(x, l1w, l1b, qw, qb, pw, pb, l2w, l2b, f1w, f1b, f2w,
                         f2b, *rest, num_heads, hidden_dropout=0.0,
                         attn_dropout=0.0, eps=1e-5, use_flash=False,
                         remat="none"):
    """All L transformer blocks as ONE `lax.scan` over stacked parameters.

    TPU-native replacement for the reference's fused_multi_transformer op
    (/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu):
    there the answer to per-layer overhead is a hand-fused CUDA megakernel; here
    the L blocks become a single scan body that XLA compiles once (layers-fold
    keeps compile time O(1) in depth) with an optional rematerialization policy
    on the body. Stacked params carry a leading [L] dim.
    """
    b, s, h = x.shape
    hd = h // num_heads
    n_layers = l1w.shape[0]
    keys = rest[0] if rest else jnp.zeros((n_layers, 2), jnp.uint32)

    def ln(z, w, bias):
        zf = z.astype(jnp.float32)
        mu = jnp.mean(zf, -1, keepdims=True)
        var = jnp.mean(jnp.square(zf - mu), -1, keepdims=True)
        return (((zf - mu) * jax.lax.rsqrt(var + eps)).astype(z.dtype) * w
                + bias)

    def drop(z, kd, salt):
        if hidden_dropout <= 0.0:
            return z
        k = jax.random.fold_in(jax.random.wrap_key_data(kd), salt)
        keep = jax.random.bernoulli(k, 1.0 - hidden_dropout, z.shape)
        return z * keep.astype(z.dtype) / (1.0 - hidden_dropout)

    def body(carry, per):
        (l1w_, l1b_, qw_, qb_, pw_, pb_, l2w_, l2b_, f1w_, f1b_, f2w_, f2b_,
         kd) = per
        y = ln(carry, l1w_, l1b_)
        qkv = tag_array(y @ qw_ + qb_, ATTN_QKV)     # [B,S,3H]
        from ..kernels.pallas.flash_attention import (
            flash_attention_blhd, flash_attention_qkv_packed,
            packed_layout_supported)
        from ..kernels.pallas.flash_pair import (flash_pair_packed,
                                                 pair_layout_supported)
        if use_flash and pair_layout_supported(hd, num_heads, s):
            # single-tile head-block kernels: zero relayouts + fused
            # single-pass dqkv backward (kernels/pallas/flash_pair.py)
            att = tag_array(flash_pair_packed(qkv, num_heads, True,
                                              dropout_rate=attn_dropout,
                                              seed=kd[0].astype(jnp.int32)),
                            ATTN_CONTEXT)
        elif use_flash and packed_layout_supported(hd):
            # fused-projection kernel for longer sequences: no head
            # split/merge inside the scan
            att = tag_array(flash_attention_qkv_packed(
                qkv, num_heads, causal=True, dropout_rate=attn_dropout,
                seed=kd[0].astype(jnp.int32)), ATTN_CONTEXT)
        elif use_flash:
            q, k, v = (t.reshape(b, s, num_heads, hd)
                       for t in jnp.split(qkv, 3, axis=-1))
            att = tag_array(flash_attention_blhd(q, k, v, causal=True,
                                                 dropout_rate=attn_dropout,
                                                 seed=kd[0].astype(jnp.int32)),
                            ATTN_CONTEXT)
        else:
            q, k, v = (t.reshape(b, s, num_heads, hd)
                       for t in jnp.split(qkv, 3, axis=-1))
            qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
            logits = (jnp.einsum("bhqd,bhkd->bhqk", qt, kt)
                      * (1.0 / math.sqrt(hd))).astype(jnp.float32)
            cm = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(cm, logits, jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(logits, axis=-1).astype(qt.dtype)
            if attn_dropout > 0.0:
                k0 = jax.random.fold_in(jax.random.wrap_key_data(kd), 0)
                keep = jax.random.bernoulli(k0, 1.0 - attn_dropout, probs.shape)
                probs = probs * keep.astype(probs.dtype) / (1.0 - attn_dropout)
            att = tag_array(
                jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", probs, vt), 1, 2),
                ATTN_CONTEXT)
        att = tag_array(att.reshape(b, s, h) @ pw_ + pb_, ATTN_OUT)
        carry = carry + drop(att, kd, 1)
        y = ln(carry, l2w_, l2b_)
        y = jax.nn.gelu(tag_array(y @ f1w_ + f1b_, MLP_HIDDEN),
                        approximate=True) @ f2w_ + f2b_
        return carry + drop(y, kd, 2), None

    if remat != "none":
        # "full" | "dots" | "selective" on the scan BODY: one jax.checkpoint
        # over the per-layer step, so the scan carries only what the policy
        # saves per layer (selective: the named linear residuals; the
        # unnamed [B,H,S,S] score/softmax region rematerializes in backward)
        note_region(remat)
        body = jax.checkpoint(body, policy=resolve_policy(remat))
    # health activation taps pause over the scan: the body's tag_array
    # values are scan-trace tracers that cannot escape to the step's
    # outputs (the discrete-block path gives per-layer RMS instead)
    from ..monitor.health import suspend_taps
    with suspend_taps():
        out, _ = jax.lax.scan(body, x, (l1w, l1b, qw, qb, pw, pb, l2w, l2b,
                                        f1w, f1b, f2w, f2b, keys))
    return out


register_op("gpt_scan_blocks", _gpt_scan_blocks_fwd, nondiff_inputs=(13,))


@dataclass
class GPTConfig:
    vocab_size: int = 50304            # 50257 padded to a multiple of 128 for the MXU
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 2048
    intermediate_size: int = 0         # 0 → 4*hidden
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    scan_layers: bool = False          # fold blocks into one lax.scan (fast compile)
    remat: str = "none"                # legacy alias of recompute_granularity
    # activation recompute (fleet/recompute.py policy layer):
    # "none" | "selective" | "dots" | "full"; interval=N checkpoints every
    # Nth block (discrete-block path; the scan path folds the policy into
    # its single body and ignores interval)
    recompute_granularity: str = "none"
    recompute_interval: int = 1

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size
        if self.recompute_granularity == "none" and self.remat != "none":
            self.recompute_granularity = self.remat   # legacy remat= spelling
        self.recompute_granularity, self.recompute_interval = \
            normalize_granularity(self.recompute_granularity,
                                  self.recompute_interval)


def gpt3_1p3b(**overrides) -> "GPTConfig":
    """GPT-3 XL, 1.3B params: 24 layers, d=2048, 16 heads (BASELINE north star)."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
               max_position_embeddings=2048)
    cfg.update(overrides)
    return GPTConfig(**cfg)


def gpt_tiny(**overrides) -> "GPTConfig":
    """Tiny config for tests / dryruns."""
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=128)
    cfg.update(overrides)
    return GPTConfig(**cfg)


# Tensor-parallel serving context (serving/engine.py sets it around its
# executable traces): a NamedSharding pinning the KV pools — and, when
# ``constrain_view`` is on, the gathered per-row views (same rank-4 axis
# order) — to the device mesh, usually head-sharded P(None, None, "model",
# None). The constraint keeps the block-axis scatter/gather SHARD-LOCAL on
# the head axis: block indices are replicated data, so each device
# scatters and gathers only its own n_kv (or hd) shard and no resharding
# ever lands inside the decode step. The view constraint is only applied
# for HEAD-axis sharding: per-head attention consumes it layout-unchanged
# there, while pinning an hd-sharded view fights GQA attention's preferred
# layout and forces XLA into full rematerializations.
_PAGED_KV_SHARD = {"sharding": None, "constrain_view": True}


def set_paged_kv_sharding(sharding, constrain_view=True):
    """Install (or clear, with None) the paged-pool sharding constraint.
    Returns the previous (sharding, constrain_view) pair so callers can
    restore it (try/finally)."""
    prev = (_PAGED_KV_SHARD["sharding"], _PAGED_KV_SHARD["constrain_view"])
    _PAGED_KV_SHARD["sharding"] = sharding
    _PAGED_KV_SHARD["constrain_view"] = bool(constrain_view)
    return prev


def _paged_kv_update(kv_cache, k, v):
    """Paged-cache write + gather, shared by GPT and LLaMA cached attention.

    ``kv_cache`` is ``(pool_k, pool_v, table, pos, write_end)``: per-layer
    [NB, BS, n_kv, hd] pools, a [B, mbs] int32 block table, the write
    cursor(s) and the exclusive end of VALID new positions. ``k``/``v`` are
    this call's fresh projections, [B, S, n_kv, hd].

    Writes scatter each position to ``(table[b, p // BS], p % BS)``;
    positions >= write_end (padded chunk tails) or beyond the table width
    redirect to trash block 0, so padding can never corrupt a live or
    shared block. Reads gather every row's blocks back into a contiguous
    [B, mbs*BS, n_kv, hd] view with ``jnp.take`` on the block axis — the
    caller's causal mask (key position <= query position) hides the stale
    tail exactly as it does for the contiguous layout. Under a tensor-
    parallel mesh (``set_paged_kv_sharding``) both the updated pools and
    the gathered views are constrained to the head-sharded placement, so
    the scatter and the gather stay shard-local on the head axis.
    """
    pool_k, pool_v, table, pos, write_end = kv_cache
    b, s = k.shape[:2]
    bs_blk = pool_k.shape[1]
    mbs = table.shape[1]
    if jnp.ndim(pos) == 1:             # per-slot cursors: decode, S == 1
        wpos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        end = write_end[:, None]
    else:                              # scalar cursor: one slot's chunk
        wpos = (pos + jnp.arange(s, dtype=jnp.int32))[None, :]
        wpos = jnp.broadcast_to(wpos, (b, s))
        end = jnp.broadcast_to(jnp.asarray(write_end)[None, None], (b, 1))
    lidx = wpos // bs_blk                                     # [B, S]
    phys = jnp.take_along_axis(table, jnp.minimum(lidx, mbs - 1), axis=1)
    phys = jnp.where((wpos < end) & (lidx < mbs), phys, 0)    # -> trash
    off = wpos % bs_blk
    pool_k = pool_k.at[phys, off].set(k.astype(pool_k.dtype))
    pool_v = pool_v.at[phys, off].set(v.astype(pool_v.dtype))
    shard = _PAGED_KV_SHARD["sharding"]
    if shard is not None:
        pool_k = jax.lax.with_sharding_constraint(pool_k, shard)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shard)
    nkv, hd = pool_k.shape[2], pool_k.shape[3]
    k_view = jnp.take(pool_k, table, axis=0).reshape(b, mbs * bs_blk, nkv, hd)
    v_view = jnp.take(pool_v, table, axis=0).reshape(b, mbs * bs_blk, nkv, hd)
    if shard is not None and _PAGED_KV_SHARD["constrain_view"]:
        k_view = jax.lax.with_sharding_constraint(k_view, shard)
        v_view = jax.lax.with_sharding_constraint(v_view, shard)
    return k_view, v_view, (pool_k, pool_v)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout_p = config.attention_dropout_prob
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_mask=None, kv_cache=None):
        if kv_cache is not None:
            return self._forward_cached(x, kv_cache)
        b, s, h = x.shape
        drop = self.dropout_p if self.training else 0.0
        from ..kernels.pallas.flash_attention import packed_layout_supported
        from ..kernels.pallas.flash_pair import pair_layout_supported
        from ..nn.functional.attention import flash_path_available
        if (self.use_flash and attn_mask is None
                and (packed_layout_supported(self.head_dim)
                     or pair_layout_supported(self.head_dim, self.num_heads, s))
                and flash_path_available(s, self.head_dim, x)):
            # packed path: the fused projection feeds the kernel directly and
            # the context comes back [b, s, h] — no head split/merge relayout
            qkv = tag_activation(self.qkv_proj(x), ATTN_QKV)
            out = F.flash_attention_qkv_packed(qkv, self.num_heads,
                                               dropout=drop, causal=True,
                                               training=self.training)
            return tag_activation(self.out_proj(out), ATTN_OUT)
        qkv = tag_activation(self.qkv_proj(x), ATTN_QKV) \
            .reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)          # each [b, s, heads, head_dim]
        if self.use_flash and attn_mask is None:
            # Pallas flash kernel on real TPUs (auto-detected, in-kernel
            # dropout); XLA sdpa otherwise
            out = F.flash_attention(q, k, v, dropout=drop, causal=True,
                                    training=self.training)
        else:
            # always causal; attn_mask (e.g. additive padding mask) combines with it
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=drop, training=self.training,
                is_causal=True)
        out = out.reshape([b, s, h])
        return tag_activation(self.out_proj(out), ATTN_OUT)

    def _forward_cached(self, x, kv_cache):
        """KV-cache attention (serving): write this chunk's K/V at `pos` and
        attend the queries over every cached position <= their own
        (reference: the cache tensors fused_multi_transformer threads
        through generation). Inference-only math on raw arrays — no tape,
        runs inside the jitted generate loop with static shapes throughout.

        Two cache layouts:
          * contiguous — ``(k_buf, v_buf, pos)`` with [B, M, nh, hd]
            buffers, each batch row owning one row;
          * paged — ``(pool_k, pool_v, table, pos, write_end)`` with
            [NB, BS, nh, hd] pools shared by all slots and a [B, mbs] int32
            block table. K/V lands at physical ``(table[b, p//BS], p%BS)``;
            the read side gathers each row's blocks back into a contiguous
            [B, mbs*BS, nh, hd] view via ``jnp.take`` on the block axis.
            Writes past ``write_end`` (padded chunk tails) or past the
            table redirect to trash block 0 so a shared or out-of-range
            block can never be corrupted by padding.

        `pos` is a scalar (one shared cursor: generate()'s lockstep batch /
        one slot's prefill chunk) or a [B] vector (per-row cursors: the
        serving engine's slots, each batch row a request at its own depth).
        """
        b, s, h = x.shape
        nh, hd = self.num_heads, self.head_dim
        qkv = self.qkv_proj(x).reshape([b, s, 3, nh, hd]).value()
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if len(kv_cache) == 5:
            pos = kv_cache[3]
            k_buf, v_buf, new_cache = _paged_kv_update(kv_cache, k, v)
        else:
            k_buf, v_buf, pos = kv_cache   # jnp arrays + int32 scalar/[B]
            if jnp.ndim(pos) == 1:
                upd = lambda buf, kv, p: jax.lax.dynamic_update_slice(
                    buf, kv, (p, 0, 0))
                k_buf = jax.vmap(upd)(k_buf, k.astype(k_buf.dtype), pos)
                v_buf = jax.vmap(upd)(v_buf, v.astype(v_buf.dtype), pos)
            else:
                k_buf = jax.lax.dynamic_update_slice(
                    k_buf, k.astype(k_buf.dtype), (0, pos, 0, 0))
                v_buf = jax.lax.dynamic_update_slice(
                    v_buf, v.astype(v_buf.dtype), (0, pos, 0, 0))
            new_cache = (k_buf, v_buf)
        if jnp.ndim(pos) == 1:
            q_pos = (pos[:, None] + jnp.arange(s))[:, None, :, None]
        else:
            q_pos = (pos + jnp.arange(s))[None, None, :, None]
        m = k_buf.shape[1]
        scores = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32),
                            k_buf.astype(jnp.float32)) / math.sqrt(hd)
        key_pos = jnp.arange(m)[None, None, None, :]
        scores = jnp.where(key_pos <= q_pos, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,bknd->bqnd", probs,
                         v_buf.astype(jnp.float32)).astype(q.dtype)
        out = self.out_proj(Tensor(ctx.reshape(b, s, h)))
        return out, new_cache


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        return self.fc_out(F.gelu(tag_activation(self.fc_in(x), MLP_HIDDEN),
                                  approximate=True))


class GPTBlock(nn.Layer):
    """Pre-LN decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None, kv_cache=None):
        if kv_cache is not None:
            a, new_cache = self.attn(self.ln_1(x), kv_cache=kv_cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x), attn_mask))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPTScannedBlocks(nn.Layer):
    """The full block stack as stacked [L, ...] parameters + one scan op.

    Self-initializing (GPT-3 recipe baked in at creation); GPTModel._init_weights
    skips these params so the stacked LN weights keep their ones/zeros init.
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        L, H, I = config.num_layers, config.hidden_size, config.intermediate_size
        self.num_heads = config.num_heads
        self.head_dim = H // config.num_heads
        self.hidden_dropout = config.hidden_dropout_prob
        self.attn_dropout = config.attention_dropout_prob
        self.eps = config.layer_norm_epsilon
        self.use_flash = config.use_flash_attention
        self.remat = config.recompute_granularity
        std = config.initializer_range
        normal = nn.initializer.Normal(mean=0.0, std=std)
        resid = nn.initializer.Normal(mean=0.0, std=std / math.sqrt(2.0 * L))
        ones = nn.initializer.Constant(1.0)
        mk = self.create_parameter
        self.ln1_weight = mk([L, H], default_initializer=ones)
        self.ln1_bias = mk([L, H], is_bias=True)
        self.qkv_weight = mk([L, H, 3 * H], default_initializer=normal)
        self.qkv_bias = mk([L, 3 * H], is_bias=True)
        self.proj_weight = mk([L, H, H], default_initializer=resid)
        self.proj_bias = mk([L, H], is_bias=True)
        self.ln2_weight = mk([L, H], default_initializer=ones)
        self.ln2_bias = mk([L, H], is_bias=True)
        self.fc1_weight = mk([L, H, I], default_initializer=normal)
        self.fc1_bias = mk([L, I], is_bias=True)
        self.fc2_weight = mk([L, I, H], default_initializer=resid)
        self.fc2_bias = mk([L, H], is_bias=True)

    def forward(self, x, attn_mask=None):
        if attn_mask is not None:
            raise ValueError("scan_layers path supports causal masking only "
                             "(attn_mask must be None)")
        b, s, _ = x.shape
        training = self.training
        drop = self.hidden_dropout if training else 0.0
        adrop = self.attn_dropout if training else 0.0
        from ..nn.functional.attention import flash_path_available
        use_flash = (self.use_flash
                     and flash_path_available(s, self.head_dim, x))
        args = [x, self.ln1_weight, self.ln1_bias, self.qkv_weight,
                self.qkv_bias, self.proj_weight, self.proj_bias,
                self.ln2_weight, self.ln2_bias, self.fc1_weight, self.fc1_bias,
                self.fc2_weight, self.fc2_bias]
        if drop > 0.0 or adrop > 0.0:
            from ..core import random as rng
            base = rng.split_key()
            L = int(self.ln1_weight.shape[0])
            from ..core.tensor import Tensor as _T
            args.append(_T(jax.random.key_data(jax.random.split(base, L))))
        return _op("gpt_scan_blocks", *args, num_heads=self.num_heads,
                   hidden_dropout=drop, attn_dropout=adrop, eps=self.eps,
                   use_flash=use_flash, remat=self.remat)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        if config.scan_layers:
            self.h = GPTScannedBlocks(config)
        else:
            self.h = nn.LayerList([GPTBlock(config)
                                   for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self._init_weights(config)

    def _init_weights(self, config):
        std = config.initializer_range
        normal = nn.initializer.Normal(mean=0.0, std=std)
        resid_scale = nn.initializer.Normal(
            mean=0.0, std=std / math.sqrt(2.0 * config.num_layers))
        for name, p in self.named_parameters():
            if config.scan_layers and name.startswith("h."):
                continue  # GPTScannedBlocks self-initializes its stacked params
            if p.ndim >= 2:
                # GPT-2/3 init: residual-out projections scaled by 1/sqrt(2L)
                init = (resid_scale if name.endswith(("out_proj.weight",
                                                      "fc_out.weight")) else normal)
                p.set_value(init(tuple(p.shape), p.dtype))

    def forward(self, input_ids, attn_mask=None, kv_caches=None,
                start_pos=None, write_end=None, layer_subset=None):
        """``layer_subset`` (non-cached path only): run just the named
        block indices — the early-exit speculative drafter's shallow pass
        over the same weights (the ``recompute_interval`` layer-selection
        idiom, applied to inference depth instead of checkpoint spacing)."""
        b, s = input_ids.shape
        if kv_caches is not None:
            if isinstance(self.h, GPTScannedBlocks):
                raise NotImplementedError(
                    "KV-cache generation requires scan_layers=False")
            p0 = start_pos if start_pos is not None else jnp.int32(0)
            if jnp.ndim(p0) == 1:
                # per-slot cursors: each batch row reads its own positions
                raw = p0[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            else:
                raw = (p0 + jnp.arange(s, dtype=jnp.int32))[None, :]
            # clamp for the LEARNED table: a padded chunk tail can step past
            # it; valid positions are engine-validated < max_pos, so the
            # clamp only ever touches garbage lanes
            pos_ids = Tensor(jnp.minimum(
                raw, self.config.max_position_embeddings - 1))
            we = write_end if write_end is not None else p0 + s
            x = self.wte(input_ids) + self.wpe(pos_ids)
            new_caches = []
            for block, cache in zip(self.h, kv_caches):
                if len(cache) == 3:    # paged: (pool_k, pool_v, block_table)
                    kc = (cache[0], cache[1], cache[2], p0, we)
                else:                  # contiguous: (k_buf, v_buf)
                    kc = (cache[0], cache[1], p0)
                x, nc = block(x, kv_cache=kc)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if isinstance(self.h, GPTScannedBlocks):
            if layer_subset is not None:
                raise NotImplementedError(
                    "layer_subset requires scan_layers=False (the scanned "
                    "stack has no per-block seam to skip at)")
            x = self.h(x, attn_mask)
        else:
            gran = self.config.recompute_granularity
            interval = self.config.recompute_interval
            from ..core import dispatch
            use_rc = (gran != "none" and self.training
                      and (dispatch.in_trace()
                           or dispatch.is_grad_enabled()))
            for i, block in enumerate(self.h):
                if layer_subset is not None and i not in layer_subset:
                    continue
                if use_rc and i % interval == 0:
                    # block forward under the recompute policy: the compiled
                    # path drops this block's residuals per `gran` and
                    # rematerializes them in backward
                    from ..distributed.fleet.recompute import recompute
                    x = recompute(block, x, attn_mask, policy=gran)
                else:
                    x = block(x, attn_mask)
        return self.ln_f(x)

    def enable_recompute(self, granularity="selective", interval: int = 1):
        """Turn activation recompute on/off after construction.

        granularity: "none" | "selective" | "dots" | "full" (True maps to
        "full", False/None to "none"); interval=N checkpoints every Nth
        block. The scan_layers path folds the policy into its single scan
        body (interval does not apply there)."""
        self.config.recompute_granularity, self.config.recompute_interval = \
            normalize_granularity(granularity, interval)
        granularity = self.config.recompute_granularity
        if isinstance(self.h, GPTScannedBlocks):
            self.h.remat = granularity
        return self

    @property
    def _recompute_wanted(self) -> bool:
        """Observability hook (jit.TrainStep emits remat/* gauges when the
        model it compiles declares recompute)."""
        return self.config.recompute_granularity != "none"


class GPTForCausalLM(nn.Layer):
    """LM head on GPTModel; loss = shifted next-token cross-entropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # reuse wte
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def enable_recompute(self, granularity="selective", interval: int = 1):
        """See GPTModel.enable_recompute."""
        self.gpt.enable_recompute(granularity, interval)
        return self

    @property
    def _recompute_wanted(self) -> bool:
        return self.gpt._recompute_wanted

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.gpt(input_ids, attn_mask)
        if labels is not None:
            # loss from the SHIFTED hidden states: the slice happens on [B,S,H]
            # (not [B,S,V]) and the head matmul + CE fuse into one executable
            tied = self.lm_head is None
            w = self.gpt.wte.weight if tied else self.lm_head.weight
            loss = _op("lm_head_ce", hidden[:, :-1, :], w, labels[:, 1:],
                       transpose_w=tied)
            # the logits are NOT materialized on the loss path — in eager that
            # second [B,S,V] projection would really execute each step. Output
            # structure is mode-independent: labels => (None, loss), always.
            return None, loss
        if self.lm_head is None:
            return ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(hidden)

    # ------------------------------------------------------------ generation

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, do_sample: bool = False,
                 top_k: int = 0, eos_token_id=None, seed=None,
                 max_length=None, use_engine: bool = False):
        """KV-cache incremental decoding, the WHOLE loop in one executable.

        Reference analog: generation over fused_multi_transformer's CacheKV
        tensors (incubate/nn/layer/fused_transformer.py:1021). TPU-native:
        prefill writes the prompt's K/V into static [B, M, nh, hd] buffers,
        then a lax.while_loop of single-token steps decodes up to
        max_new_tokens (stopping the loop early once EVERY row has emitted
        EOS) — one compiled program per (prompt_shape, max_new_tokens,
        sampling config), no per-token Python or recompiles. Greedy by
        default; do_sample=True draws from softmax(logits/temperature) with
        optional top-k; ``seed=None`` draws the sampling seed from
        ``core.random.host_generator()`` so ``paddle.seed`` makes generation
        reproducible. After an EOS a row keeps emitting EOS. Requires
        scan_layers=False (the cache threads through discrete blocks).

        ``use_engine=True`` routes through ``paddle_tpu.serving.DecodeEngine``
        (paged KV cache + slot scheduler) — same greedy tokens, and the
        engine's executables are shared with any concurrent serving traffic.
        """
        cfg = self.config
        if cfg.scan_layers:
            raise NotImplementedError(
                "generate() requires scan_layers=False")
        if max_length and max_length > cfg.max_position_embeddings:
            # GPT-specific: the LEARNED position table clamps past its end
            raise ValueError(
                f"max_length {max_length} exceeds the learned position "
                f"table ({cfg.max_position_embeddings}); positions past it "
                f"would silently clamp")
        if use_engine:
            from ..serving import generate_via_engine
            return generate_via_engine(
                self, input_ids, max_new_tokens=max_new_tokens,
                temperature=temperature, do_sample=do_sample, top_k=top_k,
                eos_token_id=eos_token_id, seed=seed, max_length=max_length)
        return _generate_with_cache(
            self, self.gpt, cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_position_embeddings,
            head_weight=(self.gpt.wte.weight if self.lm_head is None
                         else self.lm_head.weight),
            head_transpose=self.lm_head is None,
            input_ids=input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, do_sample=do_sample, top_k=top_k,
            eos_token_id=eos_token_id, seed=seed, max_length=max_length)



def shard_gpt_tp(model: "GPTForCausalLM", mesh=None, axis: str = "model"):
    """Tensor-parallel placement for GPT (the Fleet mp_layers recipe as
    NamedShardings, mirroring ``shard_llama_tp``): column-shard qkv_proj
    and fc_in (weights ``P(None, axis)``, biases ``P(axis)``), row-shard
    out_proj and fc_out (``P(axis, None)``, replicated bias — their output
    is the mp_allreduce psum), vocab-shard the token embedding (the tied
    LM head reads the same array). LayerNorms and the position table stay
    replicated. XLA's SPMD partitioner inserts the collectives; a dim not
    divisible by the axis degree is left replicated rather than refused, so
    odd geometries degrade instead of erroring."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..distributed.env import get_mesh
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return model
    tp = mesh.shape[axis]

    def put(p, spec, dim_sizes):
        if p is None or any(d % tp for d in dim_sizes):
            return
        p._data = jax.device_put(p.value(), NamedSharding(mesh, spec))

    for name, p in model.named_parameters():
        if name.endswith(("qkv_proj.weight", "fc_in.weight")):
            put(p, P(None, axis), (p.shape[1],))
        elif name.endswith(("qkv_proj.bias", "fc_in.bias")):
            put(p, P(axis), (p.shape[0],))
        elif name.endswith(("out_proj.weight", "fc_out.weight")):
            put(p, P(axis, None), (p.shape[0],))
        elif name.endswith("wte.weight"):
            put(p, P(axis, None), (p.shape[0],))
        elif name.endswith("lm_head.weight"):
            put(p, P(None, axis), (p.shape[1],))
    return model


def _lm_head_logits(hidden_last, head_weight, transpose: bool):
    """fp32 LM-head matmul over last hidden states. Shared by the eager
    compiled loop AND serving.DecodeEngine — one definition so the two
    decode paths cannot numerically drift apart (parity tests depend on
    greedy tokens matching exactly)."""
    w = head_weight.value().astype(jnp.float32)
    return hidden_last.astype(jnp.float32) @ (w.T if transpose else w)


def _pick_token(logits, key, do_sample: bool, temperature, top_k: int):
    """Greedy argmax or temperature + top-k categorical draw over [B, V]
    logits. Shared by the eager loop and the serving engine (see
    _lm_head_logits)."""
    if do_sample:
        lg = logits / jnp.maximum(temperature, 1e-6)
        if top_k and top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -1e30, lg)
        return jax.random.categorical(key, lg, axis=-1)
    return jnp.argmax(logits, axis=-1)


def _resolve_decode_horizon(s0: int, max_new_tokens: int, max_length,
                            max_pos: int, seed, do_sample: bool):
    """Shared generate() front door (eager loop AND serving's
    generate_via_engine — one definition so the two entry points cannot
    drift): validate the token budget, size the KV horizon to the DECODE
    (not the model's position table — tight M more than doubles tok/s, see
    _generate_with_cache), and derive the sampling seed. Un-seeded sampling
    draws from host_generator() so paddle.seed reproduces it; greedy never
    reads the key and must not consume the shared stream."""
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    m = int(max_length or min(s0 + max_new_tokens, max_pos))
    if s0 + max_new_tokens > m:
        raise ValueError(f"prompt {s0} + max_new_tokens {max_new_tokens} "
                         f"exceeds max_length {m}")
    if seed is None:
        if do_sample:
            from ..core.random import host_generator
            seed = int(host_generator().integers(0, 2**31 - 1))
        else:
            seed = 0
    return m, int(seed)


def _generate_with_cache(lm, backbone, num_layers: int, n_kv_heads: int,
                         head_dim: int, max_pos: int, head_weight,
                         head_transpose: bool, input_ids, max_new_tokens,
                         temperature, do_sample, top_k, eos_token_id, seed,
                         max_length):
    """Shared compiled prefill+decode loop (GPT and LLaMA): see
    GPTForCausalLM.generate for the contract. `backbone(ids, kv_caches=...,
    start_pos=...)` must return (hidden, new_caches)."""
    from ..core import dispatch

    ids_arr = input_ids.value() if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    b, s0 = ids_arr.shape
    # cache buffers sized to the DECODE, not the model's position table:
    # every step streams the whole [B, M, nh, hd] K/V pair per layer, and at
    # GPT-medium M=1024 that 0.54 GB/step read was 2.6 of the 4.9 ms step
    # (BASELINE.md round-4 decode table) — tight M more than doubled tok/s
    m, seed = _resolve_decode_horizon(s0, max_new_tokens, max_length,
                                      max_pos, seed, do_sample)
    if max_new_tokens == 0:
        return Tensor(ids_arr.astype(jnp.int32))   # same dtype as n>0 paths
    # params AND buffers: an int8-quantized model (quantize_for_serving)
    # carries its weights as Int8Linear BUFFERS — rebinding them keeps the
    # executable weight-update-safe instead of baking them in as constants
    params = [p for _, p in lm.named_parameters()] \
        + [bf for _, bf in lm.named_buffers()]
    dtype = params[0].value().dtype
    eos = -1 if eos_token_id is None else int(eos_token_id)

    def head(hidden_last):
        return _lm_head_logits(hidden_last, head_weight, head_transpose)

    def pick(logits, key):
        return _pick_token(logits, key, do_sample, temperature, top_k)

    def gen_fn(param_arrays, ids, key0):
        ctx = dispatch.TraceContext()
        saved = [p._data for p in params]
        dispatch.push_trace(ctx)
        try:
            for p, a in zip(params, param_arrays):
                p._data = a
            caches = [(jnp.zeros((b, m, n_kv_heads, head_dim), dtype),
                       jnp.zeros((b, m, n_kv_heads, head_dim), dtype))
                      for _ in range(num_layers)]
            hidden, caches = backbone(Tensor(ids), kv_caches=caches,
                                      start_pos=jnp.int32(0))
            tok0 = pick(head(hidden.value()[:, -1]), key0).astype(jnp.int32)
            done0 = tok0 == eos

            # while_loop (not scan): once EVERY row has emitted EOS the loop
            # exits — a batch that finishes in 3 tokens pays 3 steps, not
            # max_new_tokens. Unvisited columns keep the EOS fill, which is
            # exactly what finished rows would have emitted.
            out0 = jnp.full((b, max_new_tokens), max(eos, 0), jnp.int32)
            out0 = jax.lax.dynamic_update_slice(out0, tok0[:, None], (0, 0))

            def cond(carry):
                _, _, done, _, i, _ = carry
                return (i < max_new_tokens) & ~jnp.all(done)

            def step(carry):
                caches, tok, done, key, i, out = carry
                key, sub = jax.random.split(key)
                hidden, caches = backbone(
                    Tensor(tok[:, None]), kv_caches=caches,
                    start_pos=jnp.int32(s0 - 1) + i)
                nxt = pick(head(hidden.value()[:, -1]), sub).astype(jnp.int32)
                nxt = jnp.where(done, eos, nxt)      # finished rows: EOS
                done = done | (nxt == eos)
                out = jax.lax.dynamic_update_slice(out, nxt[:, None],
                                                   (jnp.int32(0), i))
                return (caches, nxt, done, key, i + jnp.int32(1), out)

            carry = jax.lax.while_loop(
                cond, step, (caches, tok0, done0, key0, jnp.int32(1), out0))
            return carry[5]
        finally:
            dispatch.pop_trace()
            ctx.restore()
            for p, d in zip(params, saved):
                p._data = d

    # per-INSTANCE executable cache (dies with the model; bounded so shape
    # churn cannot grow it without limit)
    if not hasattr(lm, "_gen_cache"):
        lm._gen_cache = {}
    # the leaf fingerprint invalidates stale closures when the model's
    # parameter/buffer STRUCTURE changes underneath us (e.g. an in-place
    # int8 swap after a generate() call): the cached gen_fn closes over the
    # old leaf list and would rebind the new arrays to the wrong tensors
    leaf_sig = tuple((tuple(p.shape), str(p.value().dtype)) for p in params)
    cache_key = (b, s0, max_new_tokens, m, do_sample, top_k,
                 float(temperature), eos, leaf_sig)
    jitted = lm._gen_cache.get(cache_key)
    if jitted is None:
        if len(lm._gen_cache) >= 8:
            lm._gen_cache.pop(next(iter(lm._gen_cache)))
        jitted = jax.jit(gen_fn)
        lm._gen_cache[cache_key] = jitted
    new_tokens = jitted(tuple(p.value() for p in params),
                        ids_arr.astype(jnp.int32), jax.random.PRNGKey(seed))
    return Tensor(jnp.concatenate(
        [ids_arr.astype(jnp.int32), new_tokens.astype(jnp.int32)], axis=1))
