"""GPT decoder-only LM — the flagship / north-star model (BASELINE.md config 4).

Architecture follows the GPT-3 recipe (pre-LN transformer decoder, learned position
embeddings, GELU MLP with 4x width, tied LM head). Built on paddle_tpu.nn layers so the
same module runs eager, under @to_static, and under mesh sharding (the distributed
wrappers re-place parameter arrays with NamedShardings; see
paddle_tpu/distributed/fleet/meta_parallel).

Reference analogs: nn.TransformerDecoderLayer surface
(/root/reference/python/paddle/nn/layer/transformer.py) and the fused incubate stack
(/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py:1021
FusedMultiTransformer) — here fusion is XLA's job, and attention uses
F.scaled_dot_product_attention (Pallas flash path on real TPUs).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .. import ops


@dataclass
class GPTConfig:
    vocab_size: int = 50304            # 50257 padded to a multiple of 128 for the MXU
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 2048
    intermediate_size: int = 0         # 0 → 4*hidden
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


def gpt3_1p3b(**overrides) -> "GPTConfig":
    """GPT-3 XL, 1.3B params: 24 layers, d=2048, 16 heads (BASELINE north star)."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
               max_position_embeddings=2048)
    cfg.update(overrides)
    return GPTConfig(**cfg)


def gpt_tiny(**overrides) -> "GPTConfig":
    """Tiny config for tests / dryruns."""
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=128)
    cfg.update(overrides)
    return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout_p = config.attention_dropout_prob
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)          # each [b, s, heads, head_dim]
        drop = self.dropout_p if self.training else 0.0
        if self.use_flash and attn_mask is None and drop == 0.0:
            # Pallas flash kernel on real TPUs (auto-detected); XLA sdpa otherwise
            out = F.flash_attention(q, k, v, causal=True)
        else:
            # always causal; attn_mask (e.g. additive padding mask) combines with it
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=drop, training=self.training,
                is_causal=True)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(nn.Layer):
    """Pre-LN decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = x + self.dropout(self.attn(self.ln_1(x), attn_mask))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self._init_weights(config)

    def _init_weights(self, config):
        std = config.initializer_range
        normal = nn.initializer.Normal(mean=0.0, std=std)
        resid_scale = nn.initializer.Normal(
            mean=0.0, std=std / math.sqrt(2.0 * config.num_layers))
        for name, p in self.named_parameters():
            if p.ndim >= 2:
                # GPT-2/3 init: residual-out projections scaled by 1/sqrt(2L)
                init = (resid_scale if name.endswith(("out_proj.weight",
                                                      "fc_out.weight")) else normal)
                p.set_value(init(tuple(p.shape), p.dtype))

    def forward(self, input_ids, attn_mask=None):
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head on GPTModel; loss = shifted next-token cross-entropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # reuse wte
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.gpt(input_ids, attn_mask)
        if self.lm_head is None:
            logits = ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        loss = F.cross_entropy(
            shift_logits.reshape([-1, self.config.vocab_size]),
            shift_labels.reshape([-1]))
        return logits, loss
