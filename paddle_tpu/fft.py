"""paddle.fft — spectral ops (reference python/paddle/fft.py, which wraps the
phi fft kernels; here each transform lowers to XLA's FFT HLO via jnp.fft)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import register_op
from .ops._helpers import _op

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _mk(name, jfn, n_arg="n"):
    def fwd(x, *, n=None, axis=-1, norm="backward"):
        kw = {n_arg: n} if n is not None else {}
        return jfn(x, axis=axis, norm=norm, **kw)

    register_op(f"fft_{name}", fwd)

    op_name = f"fft_{name}"

    def api(x, n=None, axis=-1, norm="backward", name=None):
        return _op(op_name, x, n=n, axis=axis, norm=norm)

    api.__name__ = name
    api.__doc__ = f"paddle.fft.{name} (XLA FFT lowering)."
    return api


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)


def _mk_nd(name, jfn):
    def fwd(x, *, s=None, axes=None, norm="backward"):
        kw = {"s": tuple(s) if s is not None else None,
              "axes": tuple(axes) if axes is not None else None}
        return jfn(x, norm=norm, **kw)

    register_op(f"fft_{name}", fwd)

    op_name = f"fft_{name}"

    def api(x, s=None, axes=None, norm="backward", name=None):
        s_t = tuple(s) if s is not None else None
        a_t = tuple(axes) if axes is not None else None
        return _op(op_name, x, s=s_t, axes=a_t, norm=norm)

    api.__name__ = name
    return api


fftn = _mk_nd("fftn", jnp.fft.fftn)
ifftn = _mk_nd("ifftn", jnp.fft.ifftn)
rfftn = _mk_nd("rfftn", jnp.fft.rfftn)
irfftn = _mk_nd("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=axes, norm=norm)


def _shift_fwd(x, *, axes=None, inverse=False):
    fn = jnp.fft.ifftshift if inverse else jnp.fft.fftshift
    return fn(x, axes=axes)


register_op("fft_shift", _shift_fwd)


def fftshift(x, axes=None, name=None):
    a = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return _op("fft_shift", x, axes=a, inverse=False)


def ifftshift(x, axes=None, name=None):
    a = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return _op("fft_shift", x, axes=a, inverse=True)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def _s_at(s, i):
    return None if s is None else (s[i] if i < len(s) else None)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT: fft over axes[:-1] (each with its s entry),
    hfft over the last axis."""
    y = fft(x, n=_s_at(s, 0), axis=axes[0], norm=norm)
    return hfft(y, n=_s_at(s, 1), axis=axes[-1], norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    y = ihfft(x, n=_s_at(s, 1), axis=axes[-1], norm=norm)
    return ifft(y, n=_s_at(s, 0), axis=axes[0], norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    nd = len(x.shape)
    axes = tuple(axes) if axes is not None else tuple(range(nd))
    y = x
    for i, ax in enumerate(axes[:-1]):
        y = fft(y, n=_s_at(s, i), axis=ax, norm=norm)
    return hfft(y, n=_s_at(s, len(axes) - 1), axis=axes[-1], norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    nd = len(x.shape)
    axes = tuple(axes) if axes is not None else tuple(range(nd))
    y = ihfft(x, n=_s_at(s, len(axes) - 1), axis=axes[-1], norm=norm)
    for i, ax in enumerate(axes[:-1]):
        y = ifft(y, n=_s_at(s, i), axis=ax, norm=norm)
    return y


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
