"""Buffered JSONL event sink for paddle_tpu.monitor.

One JSON object per line, schema-versioned (every record carries ``"v"``).
Writes are buffered and flushed in batches so the steady-state cost of an
event on the training thread is a dict build + list append; the file write
happens every ``flush_every`` records, on explicit flush(), and at close.

Distributed: each process writes its OWN file. Under the launcher env
contract (PADDLE_TRAINERS_NUM > 1) the path gains a ``.procN`` suffix keyed
by PADDLE_TRAINER_ID, so a multi-host run produces one JSONL per process and
tools/metrics_summary.py can aggregate them without write contention.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

SCHEMA_VERSION = 1

__all__ = ["SCHEMA_VERSION", "JsonlSink", "resolve_sink_path"]


def resolve_sink_path(path: str) -> str:
    """Key the sink file by process index in multi-process runs.

    Uses the launcher's env contract (distributed/env.py) instead of
    jax.process_index() so resolving a path never forces backend init.
    """
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    except ValueError:
        world = 1
    if world <= 1:
        return path
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    root, ext = os.path.splitext(path)
    return f"{root}.proc{rank}{ext or '.jsonl'}"


def _default(o):
    # numpy scalars / dtypes / anything exotic: degrade to repr, never raise —
    # telemetry must not be able to crash the run it is observing
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
    except Exception:
        pass
    return repr(o)


class JsonlSink:
    """Append-only buffered JSONL writer (thread-safe)."""

    def __init__(self, path: str, flush_every: int = 64,
                 resolve: bool = True):
        # resolve=False: single-writer streams that are already rank-scoped
        # (the collector's rank-0 fleet stream) must not grow a .procN suffix
        self.path = resolve_sink_path(path) if resolve else path
        self.flush_every = max(int(flush_every), 1)
        self._lock = threading.Lock()
        self._buf = []
        self._closed = False
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        # truncate: one sink instance owns one run's file
        with open(self.path, "w"):
            pass
        self.records_written = 0

    def write(self, record: dict):
        try:
            line = json.dumps(record, default=_default)
        except Exception:
            return  # never let telemetry serialization kill the run
        with self._lock:
            if self._closed:
                return
            self._buf.append(line)
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def _flush_locked(self):
        if not self._buf:
            return
        chunk = "\n".join(self._buf) + "\n"
        self._buf.clear()
        try:
            with open(self.path, "a") as f:
                f.write(chunk)
            self.records_written += chunk.count("\n")
        except OSError:
            pass

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self):
        with self._lock:
            self._flush_locked()
            self._closed = True
