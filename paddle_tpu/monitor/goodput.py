"""Goodput & MFU accounting — where every second and every FLOP goes.

The monitor's registry says *what* the run is doing and the tracer says
*which* request/step was slow; neither says where the run's wall-clock and
FLOP budget went in aggregate — the number every MFU lever is judged by.
This module is that accounting plane, two ledgers over the hooks the
monitor already receives (no new hot-path instrumentation of its own):

* **FLOP/byte ledger per executable** — at every AOT/jit mint the caller
  hands over the compiled executable; ``compiled.cost_analysis()`` FLOPs
  and bytes-accessed are captured per shape bucket (TrainStep buckets,
  DecodeEngine decode/chunk/prefill executables), with the analytical
  ``6·N·D`` model (``2·N·D`` for inference) kept as fallback *and*
  cross-check. **MFU and HFU are reported separately**: activation
  recompute replays forward FLOPs, so the hardware executes more FLOPs
  than the model's math requires — ``mfu/hfu`` counts what the chip ran
  (measured), ``mfu/mfu`` counts what the model needed (the analytic
  number when recompute is on; they coincide otherwise). A single
  conflated figure silently *rises* under ``--recompute`` while true
  model throughput falls — the exact confusion this split removes.

* **wall-clock goodput ledger** — every interval the monitor hooks report
  (dispatch spans, loader waits, compile walls, checkpoint saves, reshard
  loads, serving decode/prefill executions, scheduler overhead) lands as
  a ``(t0, t1, state, priority)`` interval; a boundary sweep folds them
  into a **gap-free, non-overlapping** per-state timeline. Overlaps are
  resolved by priority (a compile inside a dispatch window is compile
  time; an *async* checkpoint write under a running step stays invisible
  because hidden work is not lost time), the uncovered remainder is
  ``idle``, and the cumulative ``goodput/{state}_s`` gauges always sum to
  ``goodput/wall_s`` exactly — ``goodput/fraction`` is
  ``productive_s / sum(state_s)`` by construction, so the fraction always
  reconstructs from the exported per-state gauges.

Peak FLOPs resolve from the device-kind table below (the ``bench.py``
source of truth, now shared) with the ``PADDLE_PEAK_FLOPS`` env override
for device kinds the table does not know — an unknown chip degrades to
flop *counts* without utilization ratios, never to a wrong ratio.

Fleet: the per-rank ``goodput/*`` gauges ride the PR 10 collector wire
like any gauge; the aggregator derives ``fleet/goodput`` (pod goodput =
the **min** over ranks — a pod moves at its slowest rank's pace) and
names the rank that owns it, so straggler idle is attributed, not
averaged away.

Cost contract: the ledger only runs inside monitor hook bodies — the
disabled path is still the one ``monitor._active is None`` check.
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Optional

__all__ = ["GOODPUT_STATES", "PEAK_FLOPS", "GoodputLedger",
           "analytic_train_flops_per_token", "executable_cost_stats",
           "device_peak_flops", "refresh_active"]

# the gap-free timeline's states, in the (fixed) order every consumer sums
# them: goodput/fraction == productive_s / sum(<state>_s over this order)
GOODPUT_STATES = ("productive", "compile", "data_wait", "ckpt", "reshard",
                  "overhead", "idle")

# interval precedence for overlapping events, high wins. "ckpt_bg" is an
# ASYNC checkpoint write: it runs on a background thread under live steps,
# so it ranks below EVERY foreground state (productive dispatch AND host
# overhead brackets) and may only claim otherwise-idle time — hidden work
# is not lost time; a sync/emergency save blocks the loop and ranks above
# the dispatch it displaced.
_PRIORITY = {"compile": 60, "reshard": 50, "ckpt": 40, "data_wait": 30,
             "productive": 20, "overhead": 10, "ckpt_bg": 5}

# priority name -> exported state name (the two ckpt priorities are one
# accounting bucket)
_STATE_OF = {"ckpt_bg": "ckpt"}

# peak dense-matmul FLOP/s per chip by device kind (prefix match). The
# bench.py table, promoted here as the single source of truth; extend via
# env PADDLE_PEAK_FLOPS on kinds this table does not know.
PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12,
              "TPU v5p": 459e12, "TPU v6 lite": 918e12}

# fold the pending interval buffer into the cumulative sweep once it holds
# this many entries (amortizes the O(n log n) sweep to ~O(log n) per event)
_FOLD_AT = 512


def executable_cost_stats(compiled) -> Optional[dict]:
    """``{"flops", "bytes"}`` from one compiled executable's
    ``cost_analysis()`` (None when the backend does not expose it, or the
    analysis carries no flop count). Tolerates both the list-of-dicts
    (jax 0.4.x) and plain-dict shapes."""
    analyze = getattr(compiled, "cost_analysis", None)
    if analyze is None:
        return None
    try:
        ca = analyze()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None or not (float(flops) > 0):
        return None
    return {"flops": float(flops),
            "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}


def analytic_train_flops_per_token(n_params, num_layers=None,
                                   hidden_size=None, seq=None) -> float:
    """The analytic training FLOP model, ONE copy for bench.py and the
    ledger: 6 FLOPs per parameter per token (fwd 2 + bwd 4) plus the
    attention-dot term 12·L·d·S per token (scores + context, fwd+bwd),
    which parameter counting misses entirely. ``n_params`` is the caller's
    choice of parameter population — bench passes matmul params only
    (block weights + tied lm-head), the TrainStep ledger passes all
    trainable params (it cannot classify them; embeddings/norms add ~0.5%
    at GPT-medium scale)."""
    f = 6.0 * float(n_params)
    if num_layers and hidden_size and seq:
        f += 12.0 * num_layers * hidden_size * seq
    return f


def device_peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak FLOP/s for one chip: env ``PADDLE_PEAK_FLOPS`` wins (the
    escape hatch for device kinds the table does not know — without it an
    unknown chip reports ``mfu: null`` forever), else the table above by
    device-kind prefix, else None."""
    env = os.environ.get("PADDLE_PEAK_FLOPS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    return next((v for k, v in PEAK_FLOPS.items()
                 if str(device_kind).startswith(k)), None)


class _ExeCost:
    """One executable's ledger entry (per TrainStep bucket / engine exe)."""

    __slots__ = ("label", "flops", "bytes", "analytic", "tokens",
                 "recompute")

    def __init__(self, label, flops, nbytes, analytic, tokens, recompute):
        self.label = label
        self.flops = flops            # measured cost_analysis FLOPs / call
        self.bytes = nbytes
        self.analytic = analytic      # 6ND (train) / 2ND (serve) fallback
        self.tokens = tokens          # tokens one full call processes
        self.recompute = recompute    # measured FLOPs include remat replays

    def hw_flops_per_call(self):
        """What the hardware executes per call (HFU numerator)."""
        return self.flops if self.flops is not None else self.analytic

    def model_flops_per_call(self):
        """What the model's math requires per call (MFU numerator): with
        recompute the measured count conflates replays in, so the analytic
        model is the model-FLOPs source; without it the measured count IS
        the model (analytic only a fallback)."""
        if self.recompute and self.analytic is not None:
            return self.analytic
        return self.flops if self.flops is not None else self.analytic


class GoodputLedger:
    """Both ledgers over one monitor session's registry.

    All mutation happens inside monitor hook bodies (training thread,
    loader consumer, async checkpoint writer, publisher refresh), so every
    public method takes the ledger lock. Gauges are refreshed on every
    fold and on :meth:`refresh` (wired into counters emission, Prometheus
    rendering and the fleet publisher) — between refreshes only ``idle``
    can go stale, by at most one publish interval."""

    def __init__(self, registry, emit=None, peak: Optional[float] = None):
        self.registry = registry
        self._emit = emit
        self._lock = threading.Lock()
        self._anchor = time.perf_counter()
        self._cum = {s: 0.0 for s in GOODPUT_STATES if s != "idle"}
        self._pending = []            # (t0, t1, priority_name)
        self._folded_until = self._anchor
        # merged union of already-ATTRIBUTED time (folded sweeps + late
        # claims): a long interval reported after a concurrent refresh
        # folded past it (a 60s async ckpt write under a 5s fleet
        # publisher) claims exactly the gaps nothing else owned, instead
        # of losing its whole pre-watermark span to idle
        self._covered = []            # sorted disjoint (start, end)
        self._exes = {}               # (kind, key) -> _ExeCost
        self._latest = {}             # kind -> _ExeCost (jit-path fallback)
        self._hw_flops = 0.0
        self._model_flops = 0.0
        self._serve_tokens = 0
        self._serve_decode_s = 0.0    # decode-active time: the tokens/s basis
        # model FLOPs attributed to GENERATED tokens (decode + accepted
        # speculative): serve/flops_per_token's numerator. Rejected-draft
        # verify FLOPs never land here — they ride _hw_flops (HFU) only.
        self._serve_model_flops = 0.0
        self._tp = 1
        self._peak = peak
        self._peak_resolved = peak is not None

    # ------------------------------------------------------------- exe ledger

    def record_executable(self, kind: str, key, compiled, *,
                          tokens_per_call=None, analytic_flops=None,
                          recompute: bool = False, label: Optional[str]
                          = None, devices: int = 1):
        """A new executable minted: capture its cost_analysis next to the
        analytic model. ``kind`` groups buckets ("train" / "serve"),
        ``key`` identifies the bucket within it. ``devices``: how many
        chips the (SPMD) program spans — ``cost_analysis()`` reports the
        PER-DEVICE partitioned module (verified on CPU XLA), so the
        global analytic divides by the span to stay comparable, and all
        downstream MFU/HFU ratios are per-chip figures against one chip's
        peak."""
        stats = executable_cost_stats(compiled) if compiled is not None \
            else None
        devices = max(int(devices or 1), 1)
        rec = _ExeCost(label or f"{kind}_{key}",
                       stats["flops"] if stats else None,
                       stats["bytes"] if stats else None,
                       float(analytic_flops) / devices
                       if analytic_flops else None,
                       int(tokens_per_call) if tokens_per_call else None,
                       bool(recompute))
        with self._lock:
            self._exes[(kind, key)] = rec
            self._latest[kind] = rec
        g = self.registry.gauge
        if rec.flops is not None:
            g(f"mfu/{rec.label}/flops").set(rec.flops)
            g(f"mfu/{rec.label}/bytes").set(rec.bytes or 0)
        if rec.analytic is not None:
            g(f"mfu/{rec.label}/analytic_flops").set(rec.analytic)
        if rec.flops is not None and rec.tokens:
            g(f"mfu/{rec.label}/flops_per_token").set(rec.flops / rec.tokens)
        if self._emit is not None:
            self._emit("exec_cost", ledger=kind, label=rec.label,
                       flops=rec.flops, bytes=rec.bytes,
                       analytic_flops=rec.analytic,
                       tokens_per_call=rec.tokens, recompute=rec.recompute)
        return rec

    def drop_kind(self, kind: str, owner=None):
        """Executables of ``kind`` were dropped (fast-state drop rebuilds
        renumber TrainStep buckets from 1): stale per-bucket entries would
        misattribute FLOPs to dead programs. ``owner`` narrows the drop to
        one instance's entries (keys shaped ``(owner, ...)``) — a sibling
        TrainStep/engine sharing the session keeps its ledger."""
        with self._lock:
            for k in [k for k in self._exes if k[0] == kind]:
                key = k[1]
                if owner is not None and not (
                        isinstance(key, tuple) and key
                        and key[0] == owner):
                    continue
                del self._exes[k]
            self._latest.pop(kind, None)

    def set_tp(self, tp: int):
        with self._lock:
            self._tp = max(int(tp), 1)

    # -------------------------------------------------------- interval ledger

    def add(self, state: str, t0: float, t1: float):
        """One completed interval on the ``time.perf_counter`` clock.
        Out-of-order and overlapping arrivals are fine — the sweep
        resolves them; an interval reaching back before the fold
        watermark is clipped (never double-counted)."""
        with self._lock:
            self._add_locked(state, t0, t1)

    def _add_locked(self, state, t0, t1):
        t0 = max(float(t0), self._anchor)
        t1 = float(t1)
        if t1 <= t0:
            return
        wm = self._folded_until
        if t0 < wm:
            # the interval reaches into the already-folded region: claim
            # only the sub-ranges nothing else has been attributed (they
            # were idle in the fold) — never re-claim attributed time, so
            # the no-double-count invariant holds regardless of refresh
            # cadence
            self._claim_uncovered_locked(state, t0, min(t1, wm))
            t0 = wm
            if t1 <= t0:
                return
        self._pending.append((t0, t1, state))
        if len(self._pending) >= _FOLD_AT:
            self._fold_locked()
            self._refresh_locked(time.perf_counter())

    def _claim_uncovered_locked(self, state, t0, t1):
        st = _STATE_OF.get(state, state)
        claimed = []
        cur = t0
        for s, e in self._covered:
            if e <= cur:
                continue
            if s >= t1:
                break
            if s > cur:
                self._cum[st] += s - cur
                claimed.append((cur, s))
            cur = max(cur, e)
            if cur >= t1:
                break
        if cur < t1:
            self._cum[st] += t1 - cur
            claimed.append((cur, t1))
        if claimed:
            # the claims become covered too: a second late interval over
            # the same past gap cannot count it again
            self._covered.extend(claimed)
            self._merge_covered_locked()

    def _merge_covered_locked(self):
        segs = sorted(self._covered)
        out = []
        for s, e in segs:
            if out and s <= out[-1][1]:
                if e > out[-1][1]:
                    out[-1] = (out[-1][0], e)
            else:
                out.append((s, e))
        if len(out) > 1024:
            # bound memory: collapse the oldest gaps into one conservative
            # span — late claims beyond the retained horizon are dropped
            # (the pre-existing clipping behavior), never double-counted
            k = len(out) - 512
            out = [(self._anchor, out[k - 1][1])] + out[k:]
        self._covered = out

    def dispatch(self, kind: str, key, t0: float, t1: float, tokens=None,
                 generated: bool = False, host_t0=None):
        """A productive execution of one ledgered executable: the interval
        lands as ``productive`` (``host_t0``: the pre-dispatch host
        bookkeeping since the step entered, as ``overhead``), and the
        executable's FLOPs accrue to the HFU/MFU totals. ``tokens`` scales
        the *model* FLOPs to the useful fraction of the call (live slots
        of a fixed-shape decode step, valid tokens of a padded chunk) —
        the hardware ran the full program either way, which is exactly
        the serving HFU-vs-MFU gap. ``generated`` marks tokens that were
        PRODUCED (decode steps): only those count toward the serving
        throughput figure — prefill prompt tokens scale FLOPs but are not
        generation throughput (they'd inflate tokens/s ~promptlen/outlen
        on prefill-heavy workloads)."""
        with self._lock:
            self._add_locked("productive", t0, t1)
            if host_t0 is not None:
                self._add_locked("overhead", host_t0, t0)
            rec = self._exes.get((kind, key)) or self._latest.get(kind)
            attributed = 0.0
            if rec is not None:
                hw = rec.hw_flops_per_call()
                model = rec.model_flops_per_call()
                scale = 1.0
                if tokens is not None and rec.tokens:
                    scale = min(max(tokens, 0) / rec.tokens, 1.0)
                if hw:
                    self._hw_flops += hw
                if model:
                    attributed = model * scale
                    self._model_flops += attributed
            if generated:
                # tokens/s basis is DECODE-ACTIVE time, not session wall: a
                # burst's throughput must not dilute against unrelated
                # training/idle time in the same session, nor decay once
                # the burst ends
                self._serve_decode_s += max(t1 - t0, 0.0)
                if tokens:
                    self._serve_tokens += int(tokens)
                if attributed:
                    self._serve_model_flops += attributed

    # ------------------------------------------------------------------ sweep

    def _fold_locked(self):
        """Boundary sweep over the pending buffer: every instant covered
        by at least one interval is attributed to the highest-priority
        covering interval (ties break deterministically by state name),
        so states never overlap and their sum never exceeds wall time."""
        import heapq
        if not self._pending:
            return
        ivs = sorted(self._pending)
        self._pending = []
        bounds = sorted({t for iv in ivs for t in (iv[0], iv[1])})
        heap, i = [], 0
        for a, b in zip(bounds, bounds[1:]):
            while i < len(ivs) and ivs[i][0] <= a:
                t0, t1, st = ivs[i]
                heapq.heappush(heap, (-_PRIORITY.get(st, 0), st, t1))
                i += 1
            while heap and heap[0][2] <= a:
                heapq.heappop(heap)
            if heap:
                st = _STATE_OF.get(heap[0][1], heap[0][1])
                self._cum[st] += b - a
                self._covered.append((a, b))
        self._folded_until = max(self._folded_until, bounds[-1])
        self._merge_covered_locked()

    # ---------------------------------------------------------------- refresh

    def _peak_flops(self):
        if not self._peak_resolved:
            self._peak = device_peak_flops()
            self._peak_resolved = True
        return self._peak

    def refresh(self, now: Optional[float] = None) -> dict:
        """Fold + export: the ``goodput/*`` and ``mfu/*`` gauges as of
        ``now``. Returns the per-state seconds (tests and ``snapshot``
        consumers read the dict; everything else reads the gauges)."""
        with self._lock:
            self._fold_locked()
            return self._refresh_locked(
                time.perf_counter() if now is None else now)

    def _refresh_locked(self, now):
        wall = max(now - self._anchor, 0.0)
        covered = sum(self._cum.values())
        vals = dict(self._cum)
        vals["idle"] = max(wall - covered, 0.0)
        # the exported identity: fraction = productive / sum(states), the
        # sum taken in GOODPUT_STATES order so any consumer summing the
        # gauges the same way reconstructs the fraction EXACTLY
        total = sum(vals[s] for s in GOODPUT_STATES)
        g = self.registry.gauge
        for s in GOODPUT_STATES:
            g(f"goodput/{s}_s").set(vals[s])
        g("goodput/wall_s").set(wall)
        frac = vals["productive"] / total if total > 0 else 0.0
        g("goodput/fraction").set(frac)
        if self._hw_flops:
            g("mfu/hw_flops").set(self._hw_flops)
            g("mfu/model_flops").set(self._model_flops)
            peak = self._peak_flops()
            if peak and wall > 0:
                g("mfu/peak_flops").set(peak)
                g("mfu/hfu").set(self._hw_flops / (wall * peak))
                g("mfu/mfu").set(self._model_flops / (wall * peak))
        if self._serve_tokens and self._serve_decode_s > 0:
            g("serve/tokens_per_s_chip").set(
                self._serve_tokens / self._serve_decode_s / self._tp)
        if self._serve_tokens and self._serve_model_flops:
            # per-ACCEPTED-token model cost: a speculative verify bills
            # its model FLOPs pre-scaled by emitted/width, so rejected
            # drafts cannot shrink (or inflate) this figure
            g("serve/flops_per_token").set(
                self._serve_model_flops / self._serve_tokens)
        vals["wall"] = wall
        vals["fraction"] = frac
        return vals


# ------------------------------------------------------------- module plane

# the enabled monitor session's ledger (set by monitor.enable, cleared on
# teardown): lets the fleet publisher freshen the gauges it is about to
# snapshot without holding a reference into the Monitor object
_active_ledger: Optional[GoodputLedger] = None


def _set_active(ledger: Optional[GoodputLedger]):
    global _active_ledger
    _active_ledger = ledger


def refresh_active():
    """Fold + re-export the active ledger's gauges (no-op when the monitor
    is down). The fleet publisher calls this right before its registry
    snapshot so the wire always carries a current idle/fraction figure."""
    led = _active_ledger
    if led is not None:
        try:
            led.refresh()
        except Exception:
            pass  # telemetry must never take down the publisher loop
