"""Prometheus text-format rendering of monitor metrics.

Pure stdlib, NO package imports: ``tools/fleet_prom.py`` loads this module
by file path so a scrape endpoint never has to import ``paddle_tpu`` (and
with it jax) just to re-serialize JSON that is already on disk. Inputs are
plain dicts:

* a registry ``snapshot()`` — ``{"counters": {...}, "gauges": {...},
  "histograms": {...}}`` (one process's view; optional constant labels);
* a fleet record (``kind == "fleet"`` from ``run.fleet.jsonl``) — per-rank
  values become ``rank="<r>"`` labels, fleet-derived gauges render plain.

The goodput/MFU accounting plane (monitor/goodput.py) exports through the
same paths: ``goodput/fraction`` -> ``paddle_goodput_fraction``,
``mfu/hfu`` -> ``paddle_mfu_hfu`` and the per-rank fleet view carries
``paddle_fleet_goodput`` (pod goodput = min over ranks) — the live
registry render freshens the ledger first, so a scrape never reads a
stale idle figure.

Naming follows the Prometheus conventions the exposition format expects:
metric paths are sanitized (``train_step/dispatch_s`` ->
``paddle_train_step_dispatch_s``), counters gain ``_total``, histogram
summaries render as ``<name>{quantile="0.5"}`` plus ``_count``/``_sum``
(summary type — the registry keeps quantile estimates, not raw buckets).
"""
from __future__ import annotations

import re

__all__ = ["render", "render_snapshot", "render_fleet", "sanitize"]

_BAD = re.compile(r"[^a-zA-Z0-9_:]")

# HELP text for the families a dashboard needs explained at the endpoint —
# the model-health plane especially, whose numbers are meaningless without
# units/semantics. Keyed by RAW metric path; per-layer-group suffixes
# (``health/grad_norm.h.0.attn``) match their family via the ``.`` split,
# digest probes (``health/digest/p0``) via prefix. Unknown families render
# without HELP, exactly as before.
_HELP = {
    "health/nan_trips": "sampled steps whose loss or grads held NaN/Inf",
    "health/overflow_trips":
        "sampled steps with |grad| over PADDLE_HEALTH_OVERFLOW",
    "health/spikes": "loss spikes vs the rolling median/MAD window",
    "health/rollbacks": "spike rollbacks that restored a prior snapshot",
    "health/found_inf": "GradScaler-skipped updates (non-finite grads)",
    "health/loss": "last sampled loss (-1 when non-finite)",
    "health/loss_scale": "current AMP dynamic loss scale",
    "health/grad_norm": "per-layer-group gradient L2 norm (sampled)",
    "health/grad_max": "per-layer-group max |grad| over finite entries",
    "health/update_ratio": "per-layer-group update-to-weight norm ratio",
    "health/act_rms": "activation RMS at remat-tagged points (sampled)",
    "health/digest_step": "train step of the published weight digest",
    "health/digest/": "Rademacher-projection weight/grad digest probe "
                      "(cross-rank divergence comparison)",
    "serve/nan_logits": "requests terminalized for non-finite logits",
    "fleet/weight_divergence":
        "1 while one rank's weight digest disagrees with its siblings",
    "fleet/weight_diverged_rank": "the rank whose weight digest forked",
}


def _help_for(raw: str):
    fam = raw.split(".", 1)[0]
    h = _HELP.get(raw) or _HELP.get(fam)
    if h is None:
        for k, v in _HELP.items():
            if k.endswith("/") and raw.startswith(k):
                return v
    return h


def sanitize(name: str, prefix: str = "paddle") -> str:
    n = _BAD.sub("_", name.strip("/"))
    if prefix:
        n = f"{prefix}_{n}"
    if not re.match(r"[a-zA-Z_:]", n):
        n = "_" + n
    return n


def _labels(d: dict) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(d.items()))
    return "{" + inner + "}"


def _num(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _head(raw: str, name: str, typ: str, out: list):
    h = _help_for(raw)
    if h:
        out.append(f"# HELP {name} {h}")
    out.append(f"# TYPE {name} {typ}")


def _hist_lines(name: str, h: dict, labels: dict, out: list):
    """One histogram summary -> quantile + _sum/_count lines."""
    for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
        if key in h:
            out.append(f"{name}{_labels(dict(labels, quantile=q))} "
                       f"{_num(h[key])}")
    out.append(f"{name}_sum{_labels(labels)} {_num(h.get('sum', 0.0))}")
    out.append(f"{name}_count{_labels(labels)} {_num(h.get('count', 0))}")


def render_snapshot(snap: dict, labels: dict = None,
                    prefix: str = "paddle") -> str:
    """A registry ``snapshot()`` dict -> exposition text."""
    labels = dict(labels or {})
    out = []
    for raw, v in sorted((snap.get("counters") or {}).items()):
        name = sanitize(raw, prefix) + "_total"
        _head(raw, name, "counter", out)
        out.append(f"{name}{_labels(labels)} {_num(v)}")
    for raw, v in sorted((snap.get("gauges") or {}).items()):
        name = sanitize(raw, prefix)
        _head(raw, name, "gauge", out)
        out.append(f"{name}{_labels(labels)} {_num(v)}")
    for raw, h in sorted((snap.get("histograms") or {}).items()):
        if not isinstance(h, dict):
            continue
        name = sanitize(raw, prefix)
        _head(raw, name, "summary", out)
        _hist_lines(name, h, labels, out)
    return "\n".join(out) + ("\n" if out else "")


def render_fleet(rec: dict, prefix: str = "paddle") -> str:
    """One fleet record (collector schema v2) -> exposition text with
    ``rank`` labels on every per-rank series plus the fleet-derived
    gauges (step skew, liveness) and a staleness flag per rank."""
    out = []
    metrics = rec.get("metrics") or {}
    for raw, m in sorted((metrics.get("counters") or {}).items()):
        name = sanitize(raw, prefix) + "_total"
        _head(raw, name, "counter", out)
        for r, v in sorted((m.get("per_rank") or {}).items(),
                           key=lambda kv: int(kv[0])):
            out.append(f"{name}{_labels({'rank': r})} {_num(v)}")
    for raw, m in sorted((metrics.get("gauges") or {}).items()):
        name = sanitize(raw, prefix)
        _head(raw, name, "gauge", out)
        for r, v in sorted((m.get("per_rank") or {}).items(),
                           key=lambda kv: int(kv[0])):
            out.append(f"{name}{_labels({'rank': r})} {_num(v)}")
    for raw, m in sorted((metrics.get("histograms") or {}).items()):
        name = sanitize(raw, prefix)
        _head(raw, name, "summary", out)
        per = m.get("per_rank") or {}
        if per:
            for r, h in sorted(per.items(), key=lambda kv: int(kv[0])):
                _hist_lines(name, h, {"rank": r}, out)
        else:
            _hist_lines(name, m, {}, out)
    for raw, v in sorted((rec.get("derived") or {}).items()):
        name = sanitize(raw, prefix)
        _head(raw, name, "gauge", out)
        out.append(f"{name} {_num(v)}")
    stale = set(rec.get("stale") or [])
    ranks = rec.get("ranks") or []
    if ranks:
        name = sanitize("fleet/rank_stale", prefix)
        out.append(f"# TYPE {name} gauge")
        for r in sorted(set(ranks) | stale):
            out.append(f"{name}{_labels({'rank': str(r)})} "
                       f"{1 if r in stale else 0}")
    return "\n".join(out) + ("\n" if out else "")


def render(source: dict, prefix: str = "paddle") -> str:
    """Dispatch on shape: a fleet record renders per-rank, anything else is
    treated as a registry snapshot."""
    if isinstance(source, dict) and source.get("kind") == "fleet":
        return render_fleet(source, prefix=prefix)
    return render_snapshot(source or {}, prefix=prefix)
