"""Online fleet-telemetry plane: cross-rank metric aggregation.

PR 2's monitor is per-process: every rank writes its own ``run.proc<K>.jsonl``
and the fleet view only exists post-mortem when tools/metrics_summary.py
merges the files. This module is the ONLINE half (ROADMAP "one live dashboard
stream"): each rank runs a lightweight **publisher** thread that periodically
snapshots its registry as a compact delta-encoded blob and publishes it keyed
by rank + incarnation; rank 0 runs the **aggregator**, folding per-rank
snapshots into one fleet stream ``run.fleet.jsonl`` (schema v2: per-metric
``{sum, min, max, per_rank}``) plus the fleet-derived metrics no single rank
can see:

* **straggler detection** — per-rank step-duration skew over the publish
  window (``fleet/step_skew`` gauge; a WARN event names the slow rank when
  skew exceeds ``PADDLE_MONITOR_SKEW_WARN``);
* **liveness** — a rank whose blobs stop arriving goes stale
  (``fleet/ranks_stale`` gauge + flight event) within two publish intervals;
* **divergence tripwires** — a rank whose recompile or skipped-update
  counter advances ALONE is flagged (the all-ranks-vs-one-rank diagnostic
  metrics_summary does offline, moved online);
* **weight-divergence digests** — the health plane's Rademacher projection
  digests (``health/digest_step`` + ``health/digest/p<d>`` gauges, computed
  in-executable by TrainStep) are bitwise-equal across ranks holding equal
  weights; the aggregator compares them at a COMMON step (a small per-rank
  history bridges unsynchronized publish windows) and flags the rank whose
  *weights* — not just its counters — forked. Tolerance is
  ``PADDLE_HEALTH_DIGEST_RTOL`` (default 1e-5 relative).

Transport rides the launch KV master (``PADDLE_MONITOR_MASTER``, falling
back to ``PADDLE_CKPT_MASTER`` — both exported by the launch controller)
under the ``/<job>/telemetry/<rank>`` key namespace; a single-process
in-memory transport makes the whole plane testable without a launcher.

Cost contract: the publisher runs on its OWN thread — the only work it adds
anywhere near the training thread is the registry snapshot under the
registry lock, which is bounded by the metric count and measured into the
``fleet/publish_s`` histogram it publishes. The disabled path stays the
monitor's single ``_active is None`` check: nothing here installs hot-path
hooks — the collector consumes ``step_event``'s histograms, it does not
re-instrument.

Incarnation discipline (same token idea as the pod commit): every publisher
start mints ``{gen, start, token}`` where ``gen`` is the elastic restart
counter (``PADDLE_ELASTIC_RESTART``) and ``start`` the publisher birth time.
The aggregator orders incarnations by ``(gen, start)`` — a SIGKILLed rank
that restarts publishes a strictly newer incarnation and cleanly replaces
its old state; a wedged previous incarnation's late blob is rejected.
"""
from __future__ import annotations

import json
import os
import secrets
import threading
import time
import warnings
from typing import Dict, List, Optional

from . import goodput as _goodput_mod
from . import trace as _trace_mod
from .health import DIGEST_PREFIX, DIGEST_STEP_GAUGE
from .registry import Registry
from .sink import JsonlSink

__all__ = ["FLEET_SCHEMA_VERSION", "LocalTransport", "KVTransport",
           "Publisher", "Aggregator", "Collector", "start", "stop",
           "get_active", "fleet_state", "attach_elastic",
           "resolve_fleet_path"]

FLEET_SCHEMA_VERSION = 2

# counters whose single-rank advance is a divergence signature: the same
# input reaching every rank recompiles everywhere (data skew), ONE rank
# recompiling alone is that rank's placement/bucketing bug; a lone
# skipped-update means one rank saw non-finite grads the others did not
TRIPWIRE_COUNTERS = ("train_step/recompiles", "train_step/skipped_updates")

# the step-duration feed (jit/hapi already observe it via step_event)
STEP_HIST = "train_step/dispatch_s"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def resolve_fleet_path(value: Optional[str], sink_path: Optional[str]) -> str:
    """``PADDLE_MONITOR_FLEET`` contract: a truthy flag derives the stream
    path from the monitor sink's UNRESOLVED path (``run.jsonl`` ->
    ``run.fleet.jsonl``); anything else is an explicit path."""
    if value and value.lower() not in ("1", "true", "yes", "on"):
        return value
    base = sink_path or f"monitor_{os.getpid()}.jsonl"
    root, _ = os.path.splitext(base)
    return root + ".fleet.jsonl"


# ---------------------------------------------------------------- transports


class LocalTransport:
    """In-memory blob store: the single-process fallback that makes the
    publish/aggregate protocol testable without a launcher or KV master.

    Two slots per rank: ``delta`` (overwritten every publish) and ``full``
    (overwritten only on full publishes). A delta anchored on full N is
    only visible AFTER full N is (the publisher writes the full slot
    first), so the aggregator can always reconstruct exact state as
    full + latest delta — a missed intermediate blob costs nothing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._blobs: Dict[int, Dict[str, str]] = {}

    def publish(self, rank: int, blob: str, slot: str = "delta") -> bool:
        with self._lock:
            self._blobs.setdefault(int(rank), {})[slot] = blob
        return True

    def fetch_all(self) -> Dict[int, Dict[str, str]]:
        with self._lock:
            return {r: dict(slots) for r, slots in self._blobs.items()}


class KVTransport:
    """Blobs over the launch KV master (launch/master.py KVServer) under
    ``/<job>/telemetry/<rank>`` (delta slot) and ``.../<rank>/full`` —
    the same store the pod commit and the elastic heartbeats already ride.
    All failures are soft: telemetry must degrade, never take the run down
    with it."""

    def __init__(self, endpoint: str, job_id: str = "default"):
        from ..distributed.launch.master import KVClient
        self.endpoint = endpoint
        self._kv = KVClient(endpoint)
        self._prefix = f"/{job_id}/telemetry/"

    def publish(self, rank: int, blob: str, slot: str = "delta") -> bool:
        tail = f"{int(rank)}/full" if slot == "full" else f"{int(rank)}"
        return self._kv.put(f"{self._prefix}{tail}", blob)

    def fetch_all(self) -> Dict[int, Dict[str, str]]:
        out: Dict[int, Dict[str, str]] = {}
        for key, blob in self._kv.get_prefix(self._prefix).items():
            tail = key[len(self._prefix):]
            if tail.isdigit():
                out.setdefault(int(tail), {})["delta"] = blob
            elif tail.endswith("/full") and tail[:-5].isdigit():
                out.setdefault(int(tail[:-5]), {})["full"] = blob
        return out


# ----------------------------------------------------------------- publisher


class Publisher:
    """One rank's side of the plane: periodic delta-encoded registry blobs."""

    # every Nth blob re-sends the FULL snapshot: the transport only keeps a
    # rank's latest blob, so an aggregator that (re)starts mid-run would
    # otherwise never learn about metrics that settled before it joined
    FULL_EVERY = 12

    def __init__(self, registry: Registry, transport, rank: int,
                 interval: float = 5.0, generation: int = 0):
        self.registry = registry
        self.transport = transport
        self.rank = int(rank)
        self.interval = float(interval)
        self.incarnation = {"gen": int(generation), "start": time.time(),
                            "token": secrets.token_hex(4)}
        self.seq = 0
        # delta BASE: the snapshot + seq of the last FULL blob published.
        # Deltas are encoded against it — not against the previous delta —
        # and carry its seq as ``base``, so the aggregator can pair any
        # delta with the full it extends (the full lives in its own
        # transport slot); missed intermediate blobs cost nothing.
        self._base: Optional[dict] = None
        self._base_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self, full: bool = False) -> bool:
        """Snapshot -> delta -> publish. The snapshot is the only work under
        the registry lock (bounded by metric count); its cost is measured
        into fleet/publish_s so the overhead claim is a gauge, not a hope."""
        # freshen the goodput/idle gauges first: the wire must carry the
        # state as of THIS publish, not as of the last hook event (idle is
        # the one state that grows between events)
        _goodput_mod.refresh_active()
        t0 = time.perf_counter()
        snap = self.registry.snapshot()
        snap_s = time.perf_counter() - t0
        # the histogram write lands in the NEXT snapshot; self-measurement
        # must not dirty the one just taken
        self.registry.histogram("fleet/publish_s").observe(snap_s)
        full = full or self._base is None \
            or (self.seq + 1) % self.FULL_EVERY == 0
        delta = snap if full else Registry.delta(self._base, snap)
        self.seq += 1
        blob = {"v": FLEET_SCHEMA_VERSION, "rank": self.rank,
                "inc": self.incarnation, "seq": self.seq,
                "base": self.seq if full else self._base_seq,
                "ts": time.time(), "full": full,
                "counters": delta.get("counters", {}),
                "gauges": delta.get("gauges", {}),
                "hists": delta.get("histograms", {})}
        tracer = _trace_mod._active
        if tracer is not None:
            # this rank's most recent trace id rides the wire: a straggler
            # WARN on rank 0 can then name BOTH the slow rank and the trace
            # to open on that rank's run.trace.jsonl
            tid = tracer.current_trace_id()
            if tid:
                blob["trace"] = tid
        payload = json.dumps(blob)
        try:
            # full slot FIRST: a visible delta must imply its anchor full
            # is visible too (the aggregator folds full-then-delta)
            ok = (not full
                  or self.transport.publish(self.rank, payload, slot="full"))
            ok = self.transport.publish(self.rank, payload) and ok
        except Exception:
            ok = False
        if ok and full:
            self._base = snap
            self._base_seq = self.seq
        # a failed full keeps the old base: the next blob re-sends the
        # union of both windows' changes (cumulative values make that safe)
        return ok

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"fleet-pub-{self.rank}")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.publish_once()
            except Exception:
                pass  # telemetry never kills the run it observes

    def stop(self, final: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5.0)
            self._thread = None
        if final:
            try:
                self.publish_once()  # flush the tail window
            except Exception:
                pass


# ---------------------------------------------------------------- aggregator


class _RankState:
    """Aggregator-side merged view of one rank's cumulative metrics."""

    __slots__ = ("inc", "seq", "base_seq", "ts", "rx", "counters", "gauges",
                 "hists", "prev_step", "trace")

    def __init__(self, inc: dict):
        self.inc = inc
        self.seq = 0
        self.base_seq = 0  # seq of the last FULL blob folded (replace point)
        self.trace = None  # the rank's last published span-tracer trace id
        self.ts = 0.0   # publisher's clock at blob creation (display only)
        # AGGREGATOR's clock when a new blob was last accepted: liveness
        # must compare clocks from ONE host — judging the publisher's ts
        # against rank 0's clock would declare an NTP-drifted node
        # permanently stale no matter how fast it publishes
        self.rx = 0.0
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.hists: Dict[str, dict] = {}
        # (count, sum) of STEP_HIST at the previous poll — the window basis
        # for straggler math
        self.prev_step = (0, 0.0)


def _inc_order(inc: dict):
    return (int(inc.get("gen", 0)), float(inc.get("start", 0.0)))


class Aggregator:
    """Rank 0's side: fold per-rank blobs into the fleet stream + derived
    metrics. Runs on its own thread; ``poll_once`` is the deterministic unit
    tests drive directly."""

    def __init__(self, transport, world: int, fleet_path: Optional[str],
                 interval: float = 5.0, stale_after: Optional[float] = None,
                 skew_warn: float = 2.0, registry: Optional[Registry] = None,
                 emit=None, flush_every: int = 1):
        self.transport = transport
        self.world = int(world)
        self.interval = float(interval)
        # the acceptance contract: a killed rank flips ranks_stale within
        # two publish intervals
        self.stale_after = float(stale_after if stale_after is not None
                                 else 2.0 * self.interval)
        self.skew_warn = float(skew_warn)
        self.registry = registry
        self._emit = emit  # monitor event hook (flight ring + proc sink)
        self.sink = JsonlSink(fleet_path, flush_every=flush_every,
                              resolve=False) if fleet_path else None
        self.fleet_path = self.sink.path if self.sink else None
        self._ranks: Dict[int, _RankState] = {}
        self._start = time.time()
        self._warned_stale: set = set()
        self._warned_straggler: set = set()
        self._trip_streak: Dict[str, tuple] = {}
        self.digest_rtol = _env_float("PADDLE_HEALTH_DIGEST_RTOL", 1e-5)
        # per-rank {digest_step: probe vector}, bounded — the alignment
        # buffer for the cross-rank weight-digest comparison
        self._digest_hist: Dict[int, Dict[int, tuple]] = {}
        self._digest_streak = (None, 0)
        self._elastic = None
        self._elastic_mismatch = 0
        self.last_fleet: Optional[dict] = None
        self.rounds = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.sink is not None:
            self.sink.write({"v": FLEET_SCHEMA_VERSION, "kind": "fleet_meta",
                             "ts": self._start, "world": self.world,
                             "publish_s": self.interval,
                             "stale_after_s": self.stale_after,
                             "skew_warn": self.skew_warn,
                             "job": os.environ.get("PADDLE_JOB_ID",
                                                   "default")})
            self.sink.flush()

    # ------------------------------------------------------------- ingestion

    def _ingest(self, rank: int, slots: Dict[str, str]) -> None:
        blobs = []
        for slot in ("full", "delta"):  # fold order: anchor full first
            raw = slots.get(slot) if isinstance(slots, dict) else None
            if not raw:
                continue
            try:
                b = json.loads(raw)
                int(b["seq"])
                # a malformed inc must fail HERE, inside the per-blob
                # guard — not later in max(key=_inc_order), where one
                # poisoned persistent blob would abort every future poll
                if not isinstance(b["inc"], dict):
                    continue
                _inc_order(b["inc"])
            except (ValueError, KeyError, TypeError):
                continue  # torn/foreign blob: ignore
            blobs.append(b)
        if not blobs:
            return
        # the newest incarnation present wins; older slots are leftovers
        inc = max((b["inc"] for b in blobs), key=_inc_order)
        blobs = [b for b in blobs
                 if b["inc"].get("token") == inc.get("token")]
        st = self._ranks.get(rank)
        if st is not None and inc.get("token") != st.inc.get("token"):
            if _inc_order(inc) < _inc_order(st.inc):
                return  # a dead incarnation's late blob must not resurrect it
            if _inc_order(inc) == _inc_order(st.inc) \
                    and max(float(b.get("ts", 0)) for b in blobs) <= st.ts:
                return  # same-order different-token, not newer: stale
            # a NEW incarnation of this rank (restart): the cumulative
            # baseline resets with it
            st = None
        if st is None:
            st = _RankState(inc)
            self._ranks[rank] = st
            self._event("fleet_rank", rank=rank, inc=inc)
        st.inc = inc
        for b in blobs:
            self._fold(st, b)

    def _fold(self, st: _RankState, b: dict) -> None:
        """Apply one blob. Fulls REPLACE the rank's state (they are complete
        snapshots, so a metric dropped by remove_prefix disappears here
        too); deltas update it, but only when their anchor full has been
        folded — the exactness invariant that makes missed intermediate
        blobs free."""
        seq = int(b["seq"])
        if b.get("full"):
            if seq <= st.base_seq:
                return  # this full (or a newer one) is already folded
            st.counters = dict(b.get("counters") or {})
            st.gauges = dict(b.get("gauges") or {})
            st.hists = dict(b.get("hists") or {})
            st.base_seq = seq
        else:
            if seq <= st.seq:
                return  # replay of a blob already folded in
            if int(b.get("base", 0)) > st.base_seq:
                return  # anchor full not visible yet: next poll has it
            st.counters.update(b.get("counters") or {})
            st.gauges.update(b.get("gauges") or {})
            st.hists.update(b.get("hists") or {})
        if seq > st.seq:
            st.seq = seq
            st.ts = float(b.get("ts", time.time()))
            if b.get("trace"):
                st.trace = str(b["trace"])
        st.rx = time.time()

    # ------------------------------------------------------------ aggregation

    def _rank_trace(self, rank) -> Optional[str]:
        st = self._ranks.get(rank)
        return st.trace if st is not None else None

    def _digest_differs(self, a, b) -> bool:
        if len(a) != len(b):
            return True
        return any(abs(x - y) > self.digest_rtol * max(abs(x), abs(y), 1.0)
                   for x, y in zip(a, b))

    def _event(self, kind: str, **fields):
        """WARN/lifecycle events go to BOTH sides of the plane: the fleet
        stream (the live dashboard reads it) and rank 0's own monitor sink +
        flight ring (a crash report keeps the fleet context). A WARN also
        escalates the local span tracer (always-sample-on-WARN): whatever
        rank 0 had in flight when the fleet went bad survives sampling."""
        if kind == "fleet_warn":
            tracer = _trace_mod._active
            if tracer is not None:
                tracer.escalate(reason=str(fields.get("warn", "fleet")))
        if fields.get("trace", "") is None:
            del fields["trace"]  # no known trace: omit, don't write null
        rec = {"v": FLEET_SCHEMA_VERSION, "ts": time.time(), "kind": kind}
        rec.update(fields)
        if self.sink is not None:
            self.sink.write(rec)
        if self._emit is not None:
            try:
                self._emit(kind, **fields)
            except Exception:
                pass

    def _derive(self, now: float) -> dict:
        """The fleet-level metrics no single rank can compute."""
        live: List[int] = []
        stale: List[int] = []
        for r, st in sorted(self._ranks.items()):
            (stale if now - st.rx >= self.stale_after else live).append(r)
        # expected-but-never-heard ranks count stale after the grace window
        # (a rank killed before its first publish must not stay invisible)
        if now - self._start >= self.stale_after:
            for r in range(self.world):
                if r not in self._ranks:
                    stale.append(r)
        stale.sort()

        # straggler: per-rank mean step duration over THIS window
        step_s: Dict[int, float] = {}
        for r in live:
            st = self._ranks[r]
            h = st.hists.get(STEP_HIST)
            if not h:
                continue
            n, s = int(h.get("count", 0)), float(h.get("sum", 0.0))
            pn, ps = st.prev_step
            if n > pn:
                step_s[r] = (s - ps) / (n - pn)
            st.prev_step = (n, s)
        skew, slowest = 1.0, None
        if len(step_s) >= 2:
            fastest = min(step_s.values())
            slowest = max(step_s, key=step_s.get)
            if fastest > 0:
                skew = step_s[slowest] / fastest

        # divergence tripwires on cumulative VALUES, not window deltas:
        # publish windows are not synchronized across ranks, so a fleet-wide
        # startup compile lands in different polls per rank and a delta
        # comparison would cry wolf. A rank strictly AHEAD of every sibling
        # for two consecutive polls has really diverged (one poll of lead is
        # publish lag); the streak resets when the fleet catches up, so an
        # all-ranks advance (data skew) never trips it.
        diverged = []
        for name in TRIPWIRE_COUNTERS:
            vals = {r: float(self._ranks[r].counters.get(name, 0))
                    for r in live}
            leader = None
            if len(vals) > 1:
                top = max(vals.values())
                ahead = [r for r, v in vals.items() if v == top]
                if len(ahead) == 1 and top > min(vals.values()):
                    leader = ahead[0]
            prev_rank, streak = self._trip_streak.get(name, (None, 0))
            streak = streak + 1 if leader is not None \
                and leader == prev_rank else (1 if leader is not None else 0)
            self._trip_streak[name] = (leader, streak)
            if streak == 2:  # warn once on the transition, not every poll
                diverged.append({"counter": name, "rank": leader})

        # weight-divergence digests: record each live rank's latest
        # (digest_step, probe vector) into a small history, then compare all
        # ranks at the newest step EVERY digest-publishing rank has seen —
        # publish windows are unsynchronized, so rank A's freshest digest
        # may label a step rank B published two polls ago. Same two-poll
        # streak discipline as the counter tripwires: one poll of
        # disagreement can be a torn read, two is a forked rank.
        div_rank, div_step = None, None
        for r in live:
            st = self._ranks[r]
            ds = st.gauges.get(DIGEST_STEP_GAUGE)
            if ds is None:
                continue
            vec, i = [], 0
            while True:
                v = st.gauges.get(f"{DIGEST_PREFIX}p{i}")
                if v is None:
                    break
                vec.append(float(v))
                i += 1
            if not vec:
                continue
            hist = self._digest_hist.setdefault(r, {})
            hist[int(ds)] = tuple(vec)
            while len(hist) > 8:
                del hist[min(hist)]
        ranks_d = [r for r in live if self._digest_hist.get(r)]
        if len(ranks_d) >= 2:
            shared = set.intersection(
                *(set(self._digest_hist[r]) for r in ranks_d))
            if shared:
                step = max(shared)
                vecs = {r: self._digest_hist[r][step] for r in ranks_d}
                # reference = the rank the most siblings agree with (ties
                # to the lowest rank — rank 0 anchors checkpoints and this
                # aggregation, so in a 2-rank split it is the trusted side);
                # exactly ONE rank off the reference is the forked-rank
                # signature, several is seed/topology misconfiguration
                agree = {r: sum(not self._digest_differs(vecs[r], vecs[q])
                                for q in ranks_d if q != r) for r in ranks_d}
                ref = min(ranks_d, key=lambda r: (-agree[r], r))
                outliers = [r for r in ranks_d if r != ref
                            and self._digest_differs(vecs[r], vecs[ref])]
                if len(outliers) == 1:
                    div_rank, div_step = outliers[0], step
        prev_rank, streak = self._digest_streak
        streak = streak + 1 if div_rank is not None and div_rank == prev_rank \
            else (1 if div_rank is not None else 0)
        self._digest_streak = (div_rank, streak)
        if streak == 2:
            diverged.append({"counter": DIGEST_STEP_GAUGE, "rank": div_rank,
                             "kind": "weights", "step": div_step})

        derived = {"fleet/ranks": len(self._ranks), "fleet/ranks_live":
                   len(live), "fleet/ranks_stale": len(stale),
                   "fleet/step_skew": skew}
        if slowest is not None:
            derived["fleet/slowest_rank"] = slowest
        derived["fleet/weight_divergence"] = \
            1.0 if div_rank is not None and streak >= 2 else 0.0
        if div_rank is not None and streak >= 2:
            derived["fleet/weight_diverged_rank"] = div_rank

        # pod goodput (monitor/goodput.py accounting plane): a pod moves at
        # its slowest rank's pace, so pod goodput is the MIN over ranks —
        # and the lost fraction is ATTRIBUTED to the named rank (its own
        # goodput/idle_s gauge says how much of the loss is straggler idle)
        gp = {r: float(self._ranks[r].gauges["goodput/fraction"])
              for r in live
              if "goodput/fraction" in self._ranks[r].gauges}
        if gp:
            worst = min(gp, key=gp.get)
            derived["fleet/goodput"] = gp[worst]
            derived["fleet/goodput_min_rank"] = worst
            idle = self._ranks[worst].gauges.get("goodput/idle_s")
            if idle is not None:
                derived["fleet/goodput_min_rank_idle_s"] = float(idle)
        return {"live": live, "stale": stale, "step_s": step_s,
                "skew": skew, "slowest": slowest, "diverged": diverged,
                "derived": derived}

    def _warn_transitions(self, d: dict):
        """WARNs fire on the TRANSITION into a bad state (a breach episode
        is one event, not one per poll) and re-arm on recovery."""
        stale_now = set(d["stale"])
        for r in sorted(stale_now - self._warned_stale):
            self._event("fleet_warn", warn="stale", rank=r,
                        stale_after_s=self.stale_after,
                        trace=self._rank_trace(r),
                        msg=f"rank {r} missed its heartbeat: no telemetry "
                            f"blob for >= {self.stale_after:.1f}s")
        self._warned_stale = stale_now

        if d["skew"] > self.skew_warn and d["slowest"] is not None:
            r = d["slowest"]
            if r not in self._warned_straggler:
                tid = self._rank_trace(r)
                self._event(
                    "fleet_warn", warn="straggler", rank=r,
                    skew=round(d["skew"], 3), trace=tid,
                    step_s={str(k): v for k, v in d["step_s"].items()},
                    msg=f"rank {r} is the fleet straggler: step time "
                        f"{d['step_s'][r] * 1e3:.1f}ms is "
                        f"{d['skew']:.2f}x the fastest rank "
                        f"(threshold {self.skew_warn:.2f}x)"
                        + (f" [trace {tid} on rank {r}]" if tid else ""))
                self._warned_straggler.add(r)
        else:
            self._warned_straggler.clear()

        for div in d["diverged"]:
            if div.get("kind") == "weights":
                r = div["rank"]
                tid = self._rank_trace(r)
                self._event(
                    "fleet_warn", warn="weight_divergence", rank=r,
                    step=div.get("step"), trace=tid,
                    msg=f"rank {r}'s weight digest disagrees with every "
                        f"sibling at step {div.get('step')} — its WEIGHTS "
                        f"(not just its counters) have forked; eject or "
                        f"restore that rank before it poisons a collective"
                        + (f" [trace {tid} on rank {r}]" if tid else ""))
                continue
            self._event("fleet_warn", warn="divergence", rank=div["rank"],
                        counter=div["counter"],
                        trace=self._rank_trace(div["rank"]),
                        msg=f"rank {div['rank']} advanced "
                            f"{div['counter']} ALONE this window — "
                            f"one-rank divergence (placement/bucketing bug "
                            f"on that rank, not fleet-wide data skew)")

    def _check_elastic(self, d: dict):
        """The membership cross-check: ElasticManager's peer view and the
        telemetry liveness view must not silently disagree (a rank the
        elastic layer still trusts but whose telemetry died — or vice
        versa — is exactly the split-brain a restart decision must not be
        made on)."""
        mgr = self._elastic
        if mgr is None:
            return
        try:
            n_peers = len(mgr.peers())
        except Exception:
            return
        d["derived"]["fleet/elastic_peers"] = n_peers
        if n_peers != d["derived"]["fleet/ranks_live"]:
            self._elastic_mismatch += 1
            if self._elastic_mismatch == 2:  # persists past one poll: real
                self._event(
                    "fleet_warn", warn="membership_disagree",
                    elastic_peers=n_peers,
                    telemetry_live=d["derived"]["fleet/ranks_live"],
                    msg=f"elastic membership sees {n_peers} peer(s) but "
                        f"telemetry sees "
                        f"{d['derived']['fleet/ranks_live']} live rank(s)")
        else:
            self._elastic_mismatch = 0

    def poll_once(self, now: Optional[float] = None) -> dict:
        """One aggregation round: fetch -> fold -> derive -> publish."""
        now = time.time() if now is None else now
        try:
            blobs = self.transport.fetch_all()
        except Exception:
            blobs = {}
        for rank, blob in sorted(blobs.items()):
            try:
                self._ingest(rank, blob)
            except Exception:
                pass  # one rank's bad blob drops that rank, not the plane
        d = self._derive(now)
        self._check_elastic(d)
        self._warn_transitions(d)

        metrics = {"counters": {}, "gauges": {}, "histograms": {}}
        for kind, attr in (("counters", "counters"), ("gauges", "gauges"),
                           ("histograms", "hists")):
            names = set()
            for st in self._ranks.values():
                names.update(getattr(st, attr))
            for name in sorted(names):
                per = {r: getattr(st, attr)[name]
                       for r, st in sorted(self._ranks.items())
                       if name in getattr(st, attr)}
                if kind == "histograms":
                    tot = sum(int(h.get("count", 0)) for h in per.values())
                    merged = {
                        "count": tot,
                        "sum": sum(float(h.get("sum", 0.0))
                                   for h in per.values()),
                        "min": min((float(h.get("min", 0.0))
                                    for h in per.values()
                                    if h.get("count")), default=0.0),
                        "max": max((float(h.get("max", 0.0))
                                    for h in per.values()), default=0.0),
                    }
                    merged["avg"] = merged["sum"] / tot if tot else 0.0
                    for q in ("p50", "p95", "p99"):
                        merged[q] = max((float(h.get(q, 0.0))
                                         for h in per.values()), default=0.0)
                    merged["per_rank"] = {str(r): h for r, h in per.items()}
                    metrics[kind][name] = merged
                else:
                    vals = list(per.values())
                    metrics[kind][name] = {
                        "sum": sum(vals), "min": min(vals), "max": max(vals),
                        "per_rank": {str(r): v for r, v in per.items()}}

        rec = {"v": FLEET_SCHEMA_VERSION, "kind": "fleet", "ts": now,
               "round": self.rounds, "ranks": sorted(self._ranks),
               "live": d["live"], "stale": d["stale"],
               "derived": {k: (round(v, 6) if isinstance(v, float) else v)
                           for k, v in d["derived"].items()},
               "step_s": {str(r): round(v, 6)
                          for r, v in d["step_s"].items()},
               "metrics": metrics}
        self.rounds += 1
        self.last_fleet = rec
        if self.sink is not None:
            self.sink.write(rec)
            self.sink.flush()
        if self.registry is not None:
            for name, v in d["derived"].items():
                self.registry.gauge(name).set(v)
        return rec

    # -------------------------------------------------------------- lifecycle

    def attach_elastic(self, manager):
        self._elastic = manager

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-agg")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:
                pass

    def stop(self, final: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5.0)
            self._thread = None
        if final:
            try:
                self.poll_once()
            except Exception:
                pass
        if self.sink is not None:
            self.sink.close()


# ----------------------------------------------------------------- collector


class Collector:
    """One rank's whole plane membership: a publisher always, the
    aggregator + fleet sink on rank 0 only."""

    def __init__(self, registry: Registry, transport=None,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 interval: Optional[float] = None,
                 fleet_path: Optional[str] = None,
                 stale_after: Optional[float] = None,
                 skew_warn: Optional[float] = None,
                 generation: Optional[int] = None, emit=None):
        env = os.environ
        self.rank = int(env.get("PADDLE_TRAINER_ID", "0") or 0) \
            if rank is None else int(rank)
        self.world = int(env.get("PADDLE_TRAINERS_NUM", "1") or 1) \
            if world is None else int(world)
        self.interval = _env_float("PADDLE_MONITOR_PUBLISH_S", 5.0) \
            if interval is None else float(interval)
        if generation is None:
            try:
                generation = int(env.get("PADDLE_ELASTIC_RESTART", "0") or 0)
            except ValueError:
                generation = 0
        if transport is None:
            endpoint = env.get("PADDLE_MONITOR_MASTER") \
                or env.get("PADDLE_CKPT_MASTER")
            if endpoint and self.world > 1:
                transport = KVTransport(endpoint,
                                        env.get("PADDLE_JOB_ID", "default"))
            else:
                transport = LocalTransport()
        self.transport = transport
        self.publisher = Publisher(registry, transport, self.rank,
                                   interval=self.interval,
                                   generation=generation)
        self.aggregator: Optional[Aggregator] = None
        if self.rank == 0:
            if stale_after is None:
                v = env.get("PADDLE_MONITOR_STALE_S")
                stale_after = float(v) if v else None
            if skew_warn is None:
                skew_warn = _env_float("PADDLE_MONITOR_SKEW_WARN", 2.0)
            self.aggregator = Aggregator(
                transport, self.world, fleet_path, interval=self.interval,
                stale_after=stale_after, skew_warn=skew_warn,
                registry=registry, emit=emit)

    @property
    def fleet_path(self) -> Optional[str]:
        return self.aggregator.fleet_path if self.aggregator else None

    def start(self):
        self.publisher.start()
        if self.aggregator is not None:
            self.aggregator.start()
        return self

    def stop(self):
        self.publisher.stop(final=True)
        if self.aggregator is not None:
            self.aggregator.stop(final=True)

    def fleet_state(self) -> Optional[dict]:
        if self.aggregator is None or self.aggregator.last_fleet is None:
            return None
        return self.aggregator.last_fleet


# ------------------------------------------------------------- module plane

_active: Optional[Collector] = None
_lock = threading.Lock()
_pending_elastic = None


def start(registry: Optional[Registry] = None, **kw) -> Optional[Collector]:
    """Start the fleet plane over ``registry`` (default: the enabled
    monitor's). Returns None — with a warning — when there is nothing to
    attach to; telemetry is never a reason a run fails."""
    global _active
    with _lock:
        if _active is not None:
            _active.stop()
            _active = None
        if registry is None:
            from . import get as _mon_get
            mon = _mon_get()
            if mon is None:
                warnings.warn("monitor.collector.start(): the monitor is not "
                              "enabled; call monitor.enable() first",
                              RuntimeWarning)
                return None
            registry = mon.registry
            kw.setdefault("emit", mon.emit)
        try:
            col = Collector(registry, **kw)
        except Exception as e:
            warnings.warn(f"fleet collector failed to start "
                          f"({type(e).__name__}: {e}); continuing without "
                          f"online aggregation", RuntimeWarning)
            return None
        if _pending_elastic is not None and col.aggregator is not None:
            col.aggregator.attach_elastic(_pending_elastic)
        _active = col.start()
        return col


def stop():
    global _active
    with _lock:
        if _active is not None:
            _active.stop()
            _active = None


def get_active() -> Optional[Collector]:
    return _active


def fleet_state() -> Optional[dict]:
    """Rank 0's latest aggregated fleet record (None elsewhere / inactive)."""
    col = _active
    return col.fleet_state() if col is not None else None


def attach_elastic(manager):
    """Wire an ElasticManager into the aggregator's membership cross-check.
    Safe to call before start() (the next start picks it up) and on ranks
    without an aggregator (no-op)."""
    global _pending_elastic
    _pending_elastic = manager
    col = _active
    if col is not None and col.aggregator is not None:
        col.aggregator.attach_elastic(manager)
