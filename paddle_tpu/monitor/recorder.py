"""Flight recorder — bounded ring of recent telemetry for post-mortems.

Every record the monitor emits is also pushed here (cheap: deque append with
maxlen). On an uncaught exception escaping ``TrainStep.__call__`` or
``Model.fit`` — or on an explicit ``monitor.dump()`` — the ring, the full
metric-registry snapshot, and the exception are written to one JSON file, so
a crashed run leaves behind the last N events (recompiles, memory gauges,
loader stalls, step latencies) that led up to the failure.
"""
from __future__ import annotations

import json
import os
import time
import traceback
from collections import deque
from typing import Optional

from .sink import SCHEMA_VERSION, _default

__all__ = ["FlightRecorder"]


class FlightRecorder:
    def __init__(self, capacity: int = 256):
        self.capacity = max(int(capacity), 1)
        self._ring = deque(maxlen=self.capacity)
        self.events_seen = 0

    def push(self, record: dict):
        self._ring.append(record)
        self.events_seen += 1

    def events(self):
        return list(self._ring)

    def dump(self, path: str, registry_snapshot: Optional[dict] = None,
             exc: Optional[BaseException] = None,
             fleet: Optional[dict] = None,
             trace: Optional[dict] = None) -> str:
        payload = {
            "v": SCHEMA_VERSION,
            "kind": "flight_dump",
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "events_seen": self.events_seen,
            "events_kept": len(self._ring),
            "events": list(self._ring),
            "metrics": registry_snapshot or {},
        }
        if fleet is not None:
            # rank 0's last aggregated fleet snapshot (monitor/collector.py):
            # the post-mortem shows the whole fleet, not just this rank
            payload["fleet"] = fleet
        if trace is not None:
            # span-tracer context (monitor/trace.py): the stream path and
            # the open/recent trace ids — the dump names the trace to open
            payload["trace"] = trace
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:],
            }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, default=_default)
        return path
