"""Span tracer — request- and step-scoped causal telemetry.

The monitor's registry answers "what are the aggregates doing" and the
profiler answers "where did this traced window's time go"; this module
answers the question neither can: *which request or step was slow, and
which phase ate the time*. It is a Dapper-style tracer scaled down to one
process: a **trace** is one causal unit (a serving request from ``submit()``
to finish, one training step), a **span** is one phase of it (queue wait, a
chunked-prefill iteration, the AOT dispatch), and spans carry parent links
plus point **events** (a COW copy batch, a preemption, a recompile) so a
TTFT or step-time outlier decomposes exactly.

Clocks: spans are timed on ``time.perf_counter()`` (monotonic — a phase
duration can never go negative on an NTP step) and exported against a
wall-clock anchor taken once at tracer start, so trace records line up with
the monitor's ``ts`` fields and the profiler's Chrome export.

Sampling is head-based: the keep/drop decision is made when the trace
STARTS (``PADDLE_TRACE_SAMPLE``, a probability in [0, 1], default 1.0 —
a deterministic credit accumulator, not a PRNG, so a 0.1 sample really
keeps every 10th trace). Unsampled traces still buffer their spans in
memory (bounded) so a WARN fired mid-trace can **escalate** them to
sampled — the trace you need post-mortem is by construction the one the
sampler would have dropped.

Sink: schema-v1 ``run.trace.jsonl`` through the same buffered
:class:`~paddle_tpu.monitor.sink.JsonlSink` (per-process ``.procN``
suffix under the launcher env contract). A bounded in-memory ring of
finished spans feeds the profiler's Chrome export and flight dumps.

Cost contract: every integration point guards on ONE module-global
``trace._active is None`` check (the ``monitor._active`` pattern); with the
tracer enabled, an unsampled trace costs object construction and list
appends only — no serialization, no I/O.
"""
from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from .sink import JsonlSink

__all__ = ["TRACE_SCHEMA_VERSION", "Span", "Tracer", "enable", "disable",
           "enabled", "get", "current_trace_id", "escalate"]

TRACE_SCHEMA_VERSION = 1

# THE hot-path flag: integration points read this one module global and do
# nothing when it is None.
_active: Optional["Tracer"] = None

_lock = threading.Lock()


class Span:
    """One phase of a trace. ``end()`` seals it into the owning trace's
    buffer; ``event()`` attaches a point annotation (bounded — a runaway
    event stream degrades to a drop counter, never unbounded memory)."""

    MAX_EVENTS = 256

    __slots__ = ("trace", "span_id", "parent_id", "name", "kind", "t0",
                 "t1", "attrs", "events", "events_dropped")

    def __init__(self, trace: "_Trace", span_id: int, parent_id, name: str,
                 kind: str, t0: float, attrs: dict):
        self.trace = trace
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.t0 = t0
        self.t1 = None
        self.attrs = attrs
        self.events = []
        self.events_dropped = 0

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def event(self, name: str, t: Optional[float] = None, **fields):
        if len(self.events) >= self.MAX_EVENTS:
            self.events_dropped += 1
            return
        ev = {"name": name, "t": time.perf_counter() if t is None else t}
        if fields:
            ev.update(fields)
        self.events.append(ev)

    def end(self, t1: Optional[float] = None):
        if self.t1 is not None:
            return  # idempotent: a double end keeps the first boundary
        self.t1 = time.perf_counter() if t1 is None else t1
        self.trace._seal(self)

    @property
    def dur_s(self) -> float:
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0


class _Trace:
    """One causal unit: a root span plus its children, buffered until
    ``end()`` decides (sampling) whether the spans reach the sink."""

    MAX_SPANS = 512

    __slots__ = ("tracer", "trace_id", "name", "kind", "sampled",
                 "escalated", "root", "_sealed", "_dropped", "_next_span",
                 "_ended")

    def __init__(self, tracer: "Tracer", trace_id: str, name: str,
                 kind: str, sampled: bool, t0: float, attrs: dict):
        self.tracer = tracer
        self.trace_id = trace_id
        self.name = name
        self.kind = kind
        self.sampled = sampled
        self.escalated = None
        self._sealed = []          # finished spans, root excluded until end
        self._dropped = 0
        self._next_span = itertools.count(1)
        self._ended = False
        self.root = Span(self, 0, None, name, kind, t0, attrs)

    # -------------------------------------------------------------- building

    def span(self, name: str, kind: str = "phase", parent: Optional[Span]
             = None, t0: Optional[float] = None, **attrs) -> Span:
        """Open a child span (default parent: the root)."""
        return Span(self, next(self._next_span),
                    (parent or self.root).span_id, name, kind,
                    time.perf_counter() if t0 is None else t0, attrs)

    def record(self, name: str, t0: float, t1: float, kind: str = "phase",
               parent: Optional[Span] = None, **attrs) -> Span:
        """A completed span in one call (both boundaries already known)."""
        sp = self.span(name, kind=kind, parent=parent, t0=t0, **attrs)
        sp.end(t1)
        return sp

    def event(self, name: str, **fields):
        """Point annotation on the ROOT span."""
        self.root.event(name, **fields)

    def _seal(self, span: Span):
        if span.span_id == 0:
            return  # the root exports via end(), not the child buffer
        if len(self._sealed) >= self.MAX_SPANS:
            self._dropped += 1
            return
        self._sealed.append(span)

    # ------------------------------------------------------------- lifecycle

    def escalate(self, reason: str = "warn"):
        """Force-sample this trace (always-sample-on-WARN): the spans are
        already buffered, so escalation any time before ``end()`` loses
        nothing."""
        if not self.sampled:
            self.sampled = True
            self.tracer._escalated += 1
            # per-reason tally: a dump then says WHICH tripwire class
            # (health_nan, straggler, deadline...) is forcing sampling
            rs = self.tracer._escalate_reasons
            rs[reason] = rs.get(reason, 0) + 1
        if self.escalated is None:
            self.escalated = reason

    def end(self, t1: Optional[float] = None, **attrs):
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.root.attrs.update(attrs)
        self.root.end(t1)  # seals the root last — it sorts first on export
        self.tracer._finish_trace(self)


class Tracer:
    """One enabled tracing session (sink + ring + sampling state)."""

    def __init__(self, path: Optional[str] = None, *,
                 sample: Optional[float] = None, ring: int = 1024,
                 flush_every: int = 32):
        if sample is None:
            try:
                sample = float(os.environ.get("PADDLE_TRACE_SAMPLE", "")
                               or 1.0)
            except ValueError:
                sample = 1.0
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.sink = JsonlSink(path, flush_every) if path else None
        self.path = self.sink.path if self.sink else None
        # finished spans of SAMPLED traces, monotonic times kept — the
        # profiler's Chrome export and flight dumps read this
        self.ring = deque(maxlen=max(int(ring), 1))
        self._wall0 = time.time()
        self._mono0 = time.perf_counter()
        self._ids = itertools.count(1)
        self._slock = threading.Lock()
        # head-sampling credit: starts at 1.0 so the FIRST trace is always
        # kept (a short run with sample=0.1 still yields one trace);
        # sample=0.0 means "escalations only" and keeps nothing up front
        self._credit = 1.0 if self.sample > 0 else 0.0
        self._open: dict = {}          # id(trace) -> trace
        self._tls = threading.local()  # per-thread current-trace stack
        self._floating = deque(maxlen=64)
        self._last_trace_id: Optional[str] = None
        self.traces_started = 0
        self.traces_sampled = 0
        self.spans_written = 0
        self._escalated = 0
        self._escalate_reasons: Dict[str, int] = {}
        self._via_monitor = False
        if self.sink is not None:
            self.sink.write({"v": TRACE_SCHEMA_VERSION, "kind": "trace_meta",
                             "ts": self._wall0, "pid": os.getpid(),
                             "proc": int(os.environ.get("PADDLE_TRAINER_ID",
                                                        "0") or 0),
                             "sample": self.sample})

    # --------------------------------------------------------------- clocks

    def wall(self, mono: float) -> float:
        return self._wall0 + (mono - self._mono0)

    # --------------------------------------------------------------- traces

    def start_trace(self, name: str, kind: str = "trace",
                    current: bool = True, **attrs) -> _Trace:
        """Open a trace. ``current=True`` pushes it on this thread's
        current-trace stack (step traces; WARN tagging reads the top);
        serving request traces pass False — many are open at once and none
        is "the" current one. Pending floating spans (loader waits recorded
        before any trace existed) are adopted as children of the new root.
        """
        with self._slock:
            self._credit += self.sample
            sampled = self._credit >= 1.0
            if sampled:
                self._credit -= 1.0
            n = next(self._ids)
        tid = f"{os.getpid():x}-{n:x}"
        tr = _Trace(self, tid, name, kind, sampled, time.perf_counter(),
                    attrs)
        with self._slock:
            # the open-trace map is read by OTHER threads (escalate from
            # the aggregator's WARN path, snapshot_info from dump) — every
            # access goes through the lock
            self._open[id(tr)] = tr
        self._last_trace_id = tid
        self.traces_started += 1
        if current:
            stack = getattr(self._tls, "stack", None)
            if stack is None:
                stack = self._tls.stack = []
            stack.append(tr)
        if self._floating:
            # adopt only the floats addressed to this trace KIND: loader/
            # ckpt spans are step-trace context — a serving request trace
            # starting in between must not steal them
            with self._slock:
                keep, mine = deque(maxlen=self._floating.maxlen), []
                for entry in self._floating:
                    (mine if entry[0] == kind else keep).append(entry)
                self._floating = keep
            for _, name_f, t0, t1, a in mine:
                tr.record(name_f, t0, t1, **a)
        return tr

    def _finish_trace(self, tr: _Trace):
        with self._slock:
            self._open.pop(id(tr), None)
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is tr:
            stack.pop()
        if not tr.sampled:
            return
        self.traces_sampled += 1
        spans = [tr.root] + tr._sealed
        # children sealed before an escalation/late root-end keep insertion
        # order; export sorts by start so waterfalls render stably
        spans.sort(key=lambda s: (s.t0, s.span_id))
        for sp in spans:
            rec = {"v": TRACE_SCHEMA_VERSION, "kind": "span",
                   "trace": tr.trace_id, "span": sp.span_id,
                   "parent": sp.parent_id, "name": sp.name,
                   "span_kind": sp.kind, "ts": self.wall(sp.t0),
                   "dur_s": round((sp.t1 if sp.t1 is not None else sp.t0)
                                  - sp.t0, 9)}
            if sp.attrs:
                rec["attrs"] = sp.attrs
            if sp.events:
                rec["events"] = [
                    dict(e, t=self.wall(e["t"])) for e in sp.events]
            if sp.events_dropped:
                rec["events_dropped"] = sp.events_dropped
            self.ring.append({**rec, "_t0": sp.t0,
                              "_t1": sp.t1 if sp.t1 is not None else sp.t0})
            if self.sink is not None:
                self.sink.write(rec)
                self.spans_written += 1
        summary = {"v": TRACE_SCHEMA_VERSION, "kind": "trace",
                   "trace": tr.trace_id, "name": tr.name,
                   "trace_kind": tr.kind, "ts": self.wall(tr.root.t0),
                   "dur_s": round(tr.root.dur_s, 9),
                   "spans": len(spans)}
        if tr.escalated:
            summary["escalated"] = tr.escalated
        if tr._dropped:
            summary["spans_dropped"] = tr._dropped
        if tr.root.attrs:
            summary["attrs"] = tr.root.attrs
        if self.sink is not None:
            self.sink.write(summary)

    # ------------------------------------------------------------- floating

    def floating(self, name: str, t0: float, t1: float,
                 adopt_kind: str = "step", **attrs):
        """A completed span observed OUTSIDE any trace (the DeviceLoader's
        wait/fetch/H2D run before the step trace opens; a checkpoint save
        lands between steps). Buffered (bounded, cross-thread) and adopted
        as children of the next trace of ``adopt_kind`` to start — the
        step waterfall then shows the feed work that preceded the
        dispatch, and an unrelated request trace starting in between
        cannot steal it."""
        self._floating.append((adopt_kind, name, float(t0), float(t1),
                               attrs))

    # ------------------------------------------------------------ WARN hooks

    def current_trace_id(self) -> Optional[str]:
        """This thread's open trace id (top of stack), else the most
        recently started trace anywhere — what a WARN record embeds."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1].trace_id
        return self._last_trace_id

    def escalate(self, trace: Optional[_Trace] = None,
                 reason: str = "warn"):
        """Force-sample ``trace`` — or, with None, EVERY open trace (a
        fleet WARN arriving on the aggregator thread cannot know which of
        the live traces is implicated; keeping all of them is bounded by
        the open-trace count and loses nothing)."""
        if trace is not None:
            trace.escalate(reason)
            return
        with self._slock:
            targets = list(self._open.values())
        for tr in targets:
            tr.escalate(reason)

    # ------------------------------------------------------------- plumbing

    def snapshot_info(self) -> dict:
        """Flight-dump payload: where the trace stream lives and which
        traces were recently active (the crash report names the trace to
        open, not just the metrics at death)."""
        recent = []
        seen = set()
        for rec in reversed(self.ring):
            t = rec.get("trace")
            if t and t not in seen:
                seen.add(t)
                recent.append(t)
            if len(recent) >= 8:
                break
        with self._slock:
            open_ids = [tr.trace_id for tr in self._open.values()]
        return {"path": self.path, "current": self.current_trace_id(),
                "open": open_ids,
                "recent": recent, "sample": self.sample,
                "started": self.traces_started,
                "sampled": self.traces_sampled,
                "escalated": self._escalated,
                "escalated_reasons": dict(self._escalate_reasons)}

    def flush(self):
        if self.sink is not None:
            self.sink.flush()

    def close(self):
        # traces still open at close (e.g. requests in flight) are ended so
        # their spans are not silently lost
        with self._slock:
            still_open = list(self._open.values())
        for tr in still_open:
            try:
                tr.end(status="tracer_closed")
            except Exception:
                pass
        if self.sink is not None:
            self.sink.close()


# ------------------------------------------------------------------ module API


def enable(path: Optional[str] = None, *, sample: Optional[float] = None,
           ring: int = 1024, flush_every: int = 32) -> Tracer:
    """Turn the tracer on. ``path`` is the trace JSONL file (None: in-memory
    ring only); multi-process runs write ``path.procN`` per the sink
    contract. ``sample``: head-sampling probability (default: env
    ``PADDLE_TRACE_SAMPLE``, else 1.0). Idempotent-safe."""
    global _active
    with _lock:
        if _active is not None:
            _teardown_locked()
        _active = Tracer(path, sample=sample, ring=ring,
                         flush_every=flush_every)
    return _active


def _teardown_locked():
    global _active
    tr, _active = _active, None
    if tr is not None:
        tr.close()


def disable():
    with _lock:
        _teardown_locked()


def enabled() -> bool:
    return _active is not None


def get() -> Optional[Tracer]:
    return _active


def current_trace_id() -> Optional[str]:
    tr = _active
    return tr.current_trace_id() if tr is not None else None


def escalate(reason: str = "warn"):
    """Module-level always-sample-on-WARN hook (no-op when disabled)."""
    tr = _active
    if tr is not None:
        tr.escalate(reason=reason)


@atexit.register
def _atexit_close():
    # the sink buffers writes; a process that exits without disable() must
    # not lose its tail spans (open traces are ended + flushed by close)
    tr = _active
    if tr is not None:
        try:
            tr.close()
        except Exception:
            pass
