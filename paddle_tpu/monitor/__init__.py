"""paddle_tpu.monitor — always-on structured runtime telemetry.

The profiler (paddle_tpu.profiler) answers "where did this traced window's
time go"; this subsystem answers "what is the run doing, all the time":

* a metric **registry** (Counter/Gauge/Histogram) + buffered **JSONL sink**
  — one schema-versioned record per step/event, per-process files under the
  distributed launcher contract;
* a **recompile sentinel** — every TrainStep trace-cache miss / new AOT
  shape bucket emits the offending input signature, compile wall-time and a
  running count, with a ``warn_after=N`` diagnostic naming the divergent
  leaf shapes (the io/bucketing.py contract's runtime enforcement);
* **memory accounting** — per-bucket HBM estimates from
  ``compiled.memory_analysis()`` as gauges, plus a live-array census;
* a **flight recorder** — a bounded ring of recent events dumped to JSON on
  uncaught exceptions in ``TrainStep``/``Model.fit`` (or ``dump()``).

Enable with ``monitor.enable("run.jsonl")`` or env ``PADDLE_MONITOR=path``.
Disabled cost: every integration point guards on one module-global
``monitor._active is None`` check (same pattern as the profiler hook), so
the hot path stays a no-op.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
import warnings
from typing import Optional

from . import goodput as _goodput_mod
from . import health as _health_mod
from . import prom as _prom
from . import trace as _trace_mod
from .goodput import GOODPUT_STATES, GoodputLedger
from .health import HealthPlane
from .memory import executable_memory_stats, live_array_census
from .recorder import FlightRecorder
from .registry import Counter, Gauge, Histogram, Registry
from .sink import SCHEMA_VERSION, JsonlSink, resolve_sink_path

__all__ = ["enable", "disable", "enabled", "get", "emit", "dump",
           "counter", "gauge", "histogram", "snapshot", "fleet_state",
           "live_array_census", "executable_memory_stats", "prom_render",
           "Monitor", "Registry", "Counter", "Gauge", "Histogram",
           "GoodputLedger", "GOODPUT_STATES", "HealthPlane",
           "SCHEMA_VERSION"]

# THE hot-path flag: integration points read this one module global and do
# nothing when it is None. Everything else in this file is cold path.
_active: Optional["Monitor"] = None

_lock = threading.Lock()

# consumer-visible stall threshold: a q.get() that returns in under 1ms was
# not a stall, it was queue bookkeeping
_STALL_S = 1e-3

# event kinds that embed the active trace_id when the span tracer is up —
# the WARN/anomaly records an operator follows FROM metrics INTO a trace.
# ONLY kinds whose emitter runs INSIDE the implicated trace's own live
# context belong here (the backfill reads "this thread's current / most
# recent trace"). Excluded on purpose: fleet_warn / serve_preempt /
# serve_page_reject name a DIFFERENT actor's trace (their emitters attach
# it explicitly when known), and between-steps emitters (loader_stall,
# ckpt_save, preemption) would name the PREVIOUS — already ended, possibly
# unsampled — step while their floating spans land in the NEXT one.
_TRACED_KINDS = frozenset((
    "recompile", "skip_update", "fast_state_dropped", "serve_reject",
    "crash", "health_nan", "health_overflow", "health_spike"))


def _sig_json(sig):
    """Input signature tuple -> JSON-ready list (shapes/dtypes/shardings)."""
    out = []
    for entry in sig:
        try:
            shape, dtype, sharding = entry
            out.append({"shape": list(shape), "dtype": str(dtype),
                        "sharding": str(sharding)})
        except Exception:
            out.append({"repr": repr(entry)})
    return out


def _sig_divergence(prev, new):
    """Name the leaves that changed between two input signatures — the
    actionable half of a recompile event ("input[1].shape (16,128)->(16,256)"
    points straight at the bucketing boundary that leaked)."""
    if prev is None:
        return []
    diffs = []
    if len(prev) != len(new):
        diffs.append(f"arity {len(prev)}->{len(new)}")
    for i, (p, n) in enumerate(zip(prev, new)):
        pshape, pdt, pshard = p
        nshape, ndt, nshard = n
        if tuple(pshape) != tuple(nshape):
            diffs.append(f"input[{i}].shape {tuple(pshape)}->{tuple(nshape)}")
        if str(pdt) != str(ndt):
            diffs.append(f"input[{i}].dtype {pdt}->{ndt}")
        if str(pshard) != str(nshard):
            diffs.append(f"input[{i}].sharding {pshard}->{nshard}")
    return diffs


class Monitor:
    """One enabled telemetry session (registry + sink + flight recorder)."""

    def __init__(self, path: Optional[str] = None, *,
                 warn_after: Optional[int] = None, flush_every: int = 64,
                 ring: int = 256):
        self.registry = Registry()
        self.sink = JsonlSink(path, flush_every) if path else None
        self.flight = FlightRecorder(ring)
        # goodput/MFU accounting plane (monitor/goodput.py): consumes the
        # hooks below, costs nothing new on the disabled path
        self.goodput = GoodputLedger(self.registry, emit=self.emit)
        # model-health plane (monitor/health.py): numerics tripwires,
        # per-layer stats, spike rollback, divergence digests. Rides every
        # session unless PADDLE_HEALTH=0; the disabled path is still the
        # one `monitor._active is None` check at each integration point.
        self.health = _health_mod.HealthPlane(self)
        self.warn_after = warn_after
        self._op_counts = {}
        self._op_compiles = 0
        self._t0 = time.time()
        self.emit("meta", schema=SCHEMA_VERSION, pid=os.getpid(),
                  proc=int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0),
                  start=self._t0)

    # ------------------------------------------------------------- plumbing

    def emit(self, kind: str, **fields):
        """One event record: into the flight-recorder ring always, into the
        JSONL sink when one is attached. Anomaly/WARN kinds embed the span
        tracer's active trace_id when one is up, so a WARN in the metrics
        stream names the trace to open in tools/trace_view.py."""
        rec = {"v": SCHEMA_VERSION, "ts": time.time(), "kind": kind}
        rec.update(fields)
        if kind in _TRACED_KINDS and "trace" not in rec:
            tracer = _trace_mod._active
            if tracer is not None:
                tid = tracer.current_trace_id()
                if tid:
                    rec["trace"] = tid
        self.flight.push(rec)
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    def _emit_counters(self):
        # freshen the goodput/mfu gauges first: the counters record is the
        # snapshot offline tooling reads, its idle/fraction must be current
        try:
            self.goodput.refresh()
        except Exception:
            pass
        snap = self.registry.snapshot()
        # copy first: op_hook inserts first-seen op names from other threads,
        # and iterating the live dict would raise mid-dump
        snap["counters"].update({f"op/{k}": v
                                 for k, v in sorted(dict(self._op_counts)
                                                    .items())})
        self.emit("counters", metrics=snap)
        return snap

    def flush(self):
        if self.sink is not None:
            self.sink.flush()

    def close(self):
        self._emit_counters()
        if self.sink is not None:
            self.sink.close()

    # -------------------------------------------------- integration: dispatch

    def op_hook(self, name: str):
        # dict.get + store under the GIL; a rare lost increment is acceptable
        # for an op-mix profile, a per-op lock on the eager hot path is not
        c = self._op_counts
        c[name] = c.get(name, 0) + 1

    def op_compile_hook(self, name: str, attr_key):
        self._op_compiles += 1
        self.registry.counter("dispatch/op_compiles").inc()
        self.emit("op_compile", name=name, attrs=repr(attr_key),
                  count=self._op_compiles)

    # ------------------------------------------------ integration: train step

    def train_step_compiled(self, sig, prev_sig, compile_s: Optional[float],
                            count: int, path: str, compiled=None,
                            tokens=None, analytic_flops=None,
                            recompute: bool = False, span=None,
                            devices: int = 1, step_id=None):
        """Recompile-sentinel entry: a TrainStep minted a new executable.

        path: "aot" (fast-path shape bucket) | "jit" (slow-path trace-cache
        miss). Emits the recompile event, memory gauges for the new
        executable, and the warn_after diagnostic. ``tokens`` /
        ``analytic_flops`` / ``recompute`` feed the goodput plane's
        per-bucket FLOP ledger (``compiled.cost_analysis()`` measured,
        analytic 6ND as fallback + cross-check); ``span`` is the dispatch
        interval of a jit-path mint, whose compile wall is not separately
        measurable — the whole dispatch classifies as compile time.
        """
        gp = self.goodput
        # keyed per TrainStep instance (the engine_id pattern): two train
        # steps in one session never bill each other's dispatches; the
        # flat per-bucket gauges stay last-writer
        gp.record_executable("train", (step_id, count), compiled,
                             tokens_per_call=tokens,
                             analytic_flops=analytic_flops,
                             recompute=recompute, devices=devices,
                             label=f"train_bucket{count}")
        if compile_s is not None:
            now = time.perf_counter()
            gp.add("compile", now - compile_s, now)
        elif span is not None:
            gp.add("compile", span[0], span[1])
        self.registry.counter("train_step/recompiles").inc()
        self.registry.gauge("train_step/executables").set(count)
        if compile_s is not None:
            self.registry.histogram("train_step/compile_s").observe(compile_s)
        divergent = _sig_divergence(prev_sig, sig)
        self.emit("recompile", path=path, count=count, compile_s=compile_s,
                  sig=_sig_json(sig), divergent=divergent)
        if compiled is not None:
            stats = executable_memory_stats(compiled)
            if stats is not None:
                g = self.registry.gauge
                g(f"train_step/bucket{count}/argument_bytes").set(
                    stats["argument_bytes"])
                g(f"train_step/bucket{count}/output_bytes").set(
                    stats["output_bytes"])
                g(f"train_step/bucket{count}/temp_bytes").set(
                    stats["temp_bytes"])
                g(f"train_step/bucket{count}/total_bytes").set(
                    stats["total_bytes"])
                peak = self.registry.gauge("train_step/hbm_peak_bytes")
                if stats["total_bytes"] > peak.value:
                    peak.set(stats["total_bytes"])
                self.emit("memory", bucket=count, sig=_sig_json(sig), **stats)
        if self.warn_after is not None and count > self.warn_after:
            why = "; ".join(divergent) if divergent \
                else "first signature unknown"
            tracer = _trace_mod._active
            tid = tracer.current_trace_id() if tracer is not None else None
            if tracer is not None:
                # always-sample-on-WARN: the step that tripped the sentinel
                # must survive head sampling
                tracer.escalate(reason="recompile_warn")
            warnings.warn(
                f"TrainStep recompiled {count} executables "
                f"(warn_after={self.warn_after}): {why}. Unplanned shape "
                f"churn defeats the bucketing contract (io/bucketing.py) — "
                f"pad inputs to fixed boundaries or add the new shape to the "
                f"bucket set."
                + (f" [trace {tid}]" if tid else ""),
                RuntimeWarning, stacklevel=3)

    def step_event(self, dur_s: float, microbatches: int = 1, bucket=None,
                   span=None, host_t0=None, step_id=None):
        self.registry.counter("train_step/steps").inc()
        if microbatches > 1:
            self.registry.counter("train_step/microbatches").inc(microbatches)
        self.registry.histogram("train_step/dispatch_s").observe(dur_s)
        # goodput: the dispatch is productive time attributed to its shape
        # bucket's FLOP entry; host_t0 (the step's entry instant) books the
        # pre-dispatch host work as overhead
        if span is None:
            t1 = time.perf_counter()
            span = (t1 - dur_s, t1)
        self.goodput.dispatch("train", (step_id, bucket), span[0], span[1],
                              host_t0=host_t0)
        self.emit("step", dur_s=dur_s)

    # ------------------------------------------- integration: grad accumulation

    def accum_config(self, k: int, accumulator_bytes: int):
        """Gradient-accumulation gauges: microbatch count per update and the
        HBM held by the in-executable fp32 gradient accumulators."""
        self.registry.gauge("train_step/accumulate_steps").set(k)
        self.registry.gauge("train_step/grad_accumulator_bytes").set(
            accumulator_bytes)
        self.emit("accumulation", k=k, accumulator_bytes=accumulator_bytes)

    def shard_config(self, world: int, accum_bytes: int,
                     accum_ideal_bytes: int, opt_state_bytes: int,
                     buckets: int):
        """ZeRO sharding gauges: per-device residency of the fp32 grad
        accumulators (vs the 1/world_size ideal — a gap means a lost
        sharding constraint), per-device optimizer-state bytes, and how many
        fused reduce-scatter buckets the accumulation scan carries."""
        g = self.registry.gauge
        g("shard/world_size").set(world)
        g("shard/accum_bytes").set(accum_bytes)
        g("shard/accum_ideal_bytes").set(accum_ideal_bytes)
        g("shard/opt_state_bytes").set(opt_state_bytes)
        g("shard/grad_buckets").set(buckets)
        self.emit("sharding", world=world, accum_bytes=accum_bytes,
                  accum_ideal_bytes=accum_ideal_bytes,
                  opt_state_bytes=opt_state_bytes, buckets=buckets)

    def remat_compiled(self, requested: bool, regions: int, policy,
                       saved_name_bytes: int, named_bytes: dict,
                       baseline_total_bytes=None, saved_residual_bytes=None):
        """Activation-recompute gauges for a freshly minted executable.

        ``requested`` = the compiled model declared a recompute config;
        ``regions`` = checkpoint regions the trace actually applied;
        ``saved_name_bytes`` = bytes of named activations the selective
        policy keeps. ``requested`` with ``regions == 0`` (or a selective
        policy with zero named bytes) is the lost-checkpoint signature —
        the remat the user asked for silently fell out of the program.
        ``baseline_total_bytes``/``saved_residual_bytes`` are the measured
        ``memory_analysis()`` comparison against a no-remat twin when the
        caller compiled one (``PADDLE_REMAT_BASELINE=1``)."""
        g = self.registry.gauge
        g("remat/requested").set(1 if requested else 0)
        g("remat/regions").set(regions)
        g("remat/saved_name_bytes").set(saved_name_bytes)
        fields = dict(requested=bool(requested), regions=regions,
                      policy=policy, saved_name_bytes=saved_name_bytes,
                      named_bytes=dict(named_bytes or {}))
        if baseline_total_bytes is not None:
            g("remat/baseline_total_bytes").set(baseline_total_bytes)
            g("remat/saved_residual_bytes").set(saved_residual_bytes or 0)
            fields.update(baseline_total_bytes=baseline_total_bytes,
                          saved_residual_bytes=saved_residual_bytes)
        self.emit("remat", **fields)

    def update_skipped(self, microbatches: int = 1):
        """AMP found-inf: the compiled step discarded its whole update."""
        self.registry.counter("train_step/skipped_updates").inc()
        self.emit("skip_update", microbatches=microbatches)

    def placement_restored(self):
        """A user-installed array was device_put back to the compiled
        placement during fast-state refresh (cheaper than a recompile)."""
        self.registry.counter("train_step/placement_restores").inc()

    def fast_state_dropped(self, why: str, executables: int, step_id=None):
        """Fast-path executables dropped due to an unrestorable placement
        change; the next step re-lowers (recompile sentinel will fire)."""
        self.registry.counter("train_step/fast_state_drops").inc()
        # the rebuilt executables re-number from bucket 1: stale per-bucket
        # memory gauges would misattribute HBM to dead executables (same
        # rule for the goodput plane's per-bucket FLOP entries — dropped
        # for THIS TrainStep only, a sibling's entries stay live)
        self.registry.remove_prefix("train_step/bucket")
        self.registry.remove_prefix("mfu/train_bucket")
        self.goodput.drop_kind("train", owner=step_id)
        self.emit("fast_state_dropped", reason=why, executables=executables)

    # ---------------------------------------------------- integration: loader

    def loader_wait(self, wait_s: float, qsize: int, span=None):
        self.registry.counter("loader/batches").inc()
        self.registry.gauge("loader/queue_depth").set(qsize)
        self.registry.histogram("loader/wait_s").observe(wait_s)
        # goodput: consumer-visible feed wait is data_wait — the producer's
        # hidden fetch/H2D never reaches the ledger (hidden work is not
        # lost time)
        if span is None:
            t1 = time.perf_counter()
            span = (t1 - wait_s, t1)
        self.goodput.add("data_wait", span[0], span[1])
        if wait_s > _STALL_S:
            self.registry.counter("loader/stalls").inc()
            self.emit("loader_stall", wait_s=wait_s, qsize=qsize)

    # ------------------------------------------------------ integration: hapi

    def epoch_event(self, epoch: int, steps: int, wall_s: float, logs: dict):
        self.registry.counter("fit/epochs").inc()
        self.registry.histogram("fit/epoch_s").observe(wall_s)
        self.emit("epoch", epoch=epoch, steps=steps, wall_s=wall_s,
                  logs={k: float(v) for k, v in (logs or {}).items()})

    # ---------------------------------------------- integration: checkpointing

    def ckpt_saved(self, step: int, nbytes: int, dur_s: float, mode: str,
                   attempts: int = 1):
        """A snapshot committed. mode: "sync" | "async" | "emergency"."""
        self.registry.counter("ckpt/saves").inc()
        if mode == "emergency":
            self.registry.counter("ckpt/emergency_saves").inc()
        self.registry.gauge("ckpt/last_step").set(step)
        self.registry.gauge("ckpt/last_bytes").set(nbytes)
        self.registry.histogram("ckpt/save_s").observe(dur_s)
        # goodput: a sync/emergency save blocks the loop (ckpt time); an
        # async write runs under live steps and may only claim time nothing
        # foreground owns — the interval ledger's priorities encode that
        now = time.perf_counter()
        self.goodput.add("ckpt_bg" if mode == "async" else "ckpt",
                         now - dur_s, now)
        self.emit("ckpt_save", step=step, bytes=nbytes, dur_s=dur_s,
                  mode=mode, attempts=attempts)

    def ckpt_blocked(self, t0: float, t1: float):
        """Host time the fit loop spent inside save() (the async path's
        host snapshot; the whole write when blocking) — foreground
        checkpoint time for the goodput ledger, perf_counter interval."""
        self.goodput.add("ckpt", t0, t1)

    def ckpt_retry(self, step: int, attempt: int):
        """A snapshot write attempt failed transiently and is being retried."""
        self.registry.counter("ckpt/retries").inc()
        self.emit("ckpt_retry", step=step, attempt=attempt)

    def ckpt_corrupt(self, path: str, why: str,
                     quarantined: Optional[str] = None):
        """Auto-resume skipped a torn/corrupt snapshot (quarantined when it
        could be renamed out of the resume scan)."""
        self.registry.counter("ckpt/corrupt_skipped").inc()
        self.emit("ckpt_corrupt", path=path, why=why, quarantined=quarantined)

    def ckpt_resumed(self, step: int, path: str):
        self.registry.counter("ckpt/resumes").inc()
        self.emit("ckpt_resume", step=step, path=path)

    def preempted(self, signum: int):
        """A watched preemption signal arrived (SIGTERM/SIGINT)."""
        self.registry.counter("preempt/signals").inc()
        self.emit("preemption", signum=int(signum))

    def reshard_loaded(self, src_world: int, dst_world: int, arrays: int,
                       identity: int, mapped: int, gathered: int,
                       nestable_gather: int, bytes_read: int, wall_s: float):
        """A checkpoint restore resharded an N-way snapshot onto this mesh.

        ``nestable_gather`` counts arrays that fell back to the
        gather-then-re-place path even though the WORLD pair nests
        (N%M==0 or M%N==0) — an array's sharded dim moved between worlds,
        paying a full-size host buffer the index-mapped reader would have
        avoided. tools/metrics_summary.py WARNs on it."""
        g = self.registry.gauge
        g("reshard/src_world").set(src_world)
        g("reshard/dst_world").set(dst_world)
        g("reshard/arrays").set(arrays)
        g("reshard/arrays_identity").set(identity)
        g("reshard/arrays_mapped").set(mapped)
        g("reshard/arrays_gathered").set(gathered)
        g("reshard/bytes_read").set(bytes_read)
        self.registry.counter("reshard/loads").inc()
        now = time.perf_counter()
        self.goodput.add("reshard", now - wall_s, now)
        if nestable_gather:
            self.registry.counter("reshard/nestable_gather_fallbacks").inc(
                nestable_gather)
        self.registry.histogram("reshard/load_s").observe(wall_s)
        self.emit("reshard", src_world=src_world, dst_world=dst_world,
                  arrays=arrays, identity=identity, mapped=mapped,
                  gathered=gathered, nestable_gather=nestable_gather,
                  bytes_read=bytes_read, wall_s=wall_s)

    # ----------------------------------------------------- integration: serving

    def serve_engine(self, max_slots: int, max_len: int, buckets, quantize,
                     engine_id=None, paged=None, block_size=None,
                     kv_blocks=None, prefill_chunk=None, tp=1,
                     drafter=None):
        """A DecodeEngine came up: record its static geometry (paged
        engines add the block pool shape and the prefill chunk size; a
        mesh-native engine carries its tensor-parallel degree; a
        speculative engine names its drafter)."""
        g = self.registry.gauge
        g("serve/max_slots").set(max_slots)
        g("serve/max_len").set(max_len)
        if kv_blocks:
            g("serve/kv_blocks").set(kv_blocks)
            g("serve/block_size").set(block_size or 0)
        if tp and tp > 1:
            g("serve/tp").set(tp)
        self.goodput.set_tp(tp or 1)   # tokens/s/chip divides by the mesh
        self.emit("serve_engine", max_slots=max_slots, max_len=max_len,
                  prefill_buckets=list(buckets), quantize=quantize,
                  engine=engine_id, paged=paged, block_size=block_size,
                  kv_blocks=kv_blocks, prefill_chunk=prefill_chunk, tp=tp,
                  drafter=drafter)

    def serve_compiled(self, kind: str, bucket, compile_s: float, count: int,
                       engine_id=None, compiled=None, tokens=None,
                       analytic_flops=None, devices: int = 1):
        """Serving recompile sentinel: the engine minted an executable.
        kind: "prefill" (one per prompt-length bucket) | "decode" (exactly
        one per ENGINE, ever — a second decode mint from the same engine in
        steady state is a bug; `engine_id` lets a sink with several engines
        tell re-mints from a sibling engine's first mint). ``compiled`` /
        ``tokens`` / ``analytic_flops`` / ``devices`` (the engine's TP
        span) feed the goodput FLOP ledger, keyed per ENGINE so two live
        engines in one session never bill each other's dispatches (the
        flat per-bucket gauges stay last-writer, like the serve/* geometry
        gauges)."""
        label = f"serve_{kind}" + (str(bucket) if bucket else "")
        gp = self.goodput
        rec = gp.record_executable("serve", (engine_id, kind, bucket),
                                   compiled, tokens_per_call=tokens,
                                   analytic_flops=analytic_flops,
                                   devices=devices, label=label)
        if kind == "decode" and rec.tokens:
            # per-token serving cost (model-FLOPs/token next to TTFT in
            # the reports) is a DECODE figure: a prefill bucket minting
            # later must not overwrite it with its own per-token cost
            mf = rec.model_flops_per_call()
            if mf is not None:
                self.registry.gauge("serve/model_flops_per_token").set(
                    mf / rec.tokens)
        now = time.perf_counter()
        gp.add("compile", now - compile_s, now)
        self.registry.counter("serve/compiles").inc()
        self.registry.counter(f"serve/compiles_{kind}").inc()
        self.registry.gauge("serve/executables").set(count)
        self.registry.histogram("serve/compile_s").observe(compile_s)
        self.emit("serve_compile", path=kind, bucket=bucket,
                  compile_s=compile_s, count=count, engine=engine_id)

    def serve_request(self, queued: bool, error: Optional[str] = None,
                      overload: bool = False, draining: bool = False):
        """submit() outcome: admitted to the queue, or rejected at the door
        (malformed requests never reach a slot; ``overload`` marks a
        well-formed request bounced off a full admission queue;
        ``draining`` one bounced off a draining engine's closed door)."""
        if queued:
            self.registry.counter("serve/requests").inc()
        else:
            self.registry.counter("serve/rejected").inc()
            if overload:
                self.registry.counter("serve/rejected_overload").inc()
            if draining:
                self.registry.counter("serve/rejected_draining").inc()
            self.emit("serve_reject", error=error, overload=overload,
                      draining=draining)

    def serve_queue_wait(self, wait_s: float):
        """Time a request sat in the admission queue before its slot
        (saturation made visible: the queue is bounded, the wait is
        measured)."""
        self.registry.histogram("serve/queue_wait_s").observe(wait_s)

    def serve_page_reject(self, free_blocks: int, needed_blocks: int,
                          trace_id=None, pool_blocks: int = 0):
        """Paged admission refused for lack of KV blocks. ``free >=
        needed`` in this event is the allocator-bug signature (refusal
        without real pressure) that metrics_summary WARNs on — except
        when ``pool_blocks > 0``: the admission adopted that many blocks
        from the cross-process pool before refusing, so the adopted
        blocks legitimately sit between "free" and "needed" and the WARN
        predicate must skip the record. ``trace_id``: the refused
        REQUEST's trace (more precise than the generic most-recent-trace
        tag)."""
        self.registry.counter("serve/page_rejects").inc()
        fields = dict(free_blocks=int(free_blocks),
                      needed_blocks=int(needed_blocks))
        if pool_blocks:
            fields["pool_blocks"] = int(pool_blocks)
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_page_reject", **fields)

    def serve_preempted(self, nth: int, trace_id=None):
        """Pool pressure evicted a tenant back to the queue (its compute
        is redone on re-admission). ``trace_id``: the VICTIM request's
        trace."""
        self.registry.counter("serve/preemptions").inc()
        fields = dict(nth=int(nth))
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_preempt", **fields)

    def serve_nan_logits(self, where: str, trace_id=None):
        """The decode/prefill executable reported non-finite logits for a
        request; the engine terminalizes it as `failed` instead of
        streaming garbage tokens. ``where``: which executable tripped
        (prefill/chunk/decode/verify)."""
        self.registry.counter("serve/nan_logits").inc()
        fields = dict(where=where)
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_nan_logits", **fields)

    def serve_paged(self, pager_stats, kv_util: float, engine_id=None):
        """Per-decode-step paged-pool gauges (cheap sets, no event). The
        cumulative preemption count lives in the serve/preemptions COUNTER
        (serve_preempted), not a gauge here — a same-named gauge tripped
        the registry's no-silent-shadowing check. ``engine_id`` adds a
        per-engine ``serve/prefix_hits.eng<id>`` mirror so a multi-engine
        process (router bench/e2e) can attribute cache wins per replica —
        the affinity-beats-round-robin gate sums exactly these."""
        g = self.registry.gauge
        if engine_id is not None:
            g(f"serve/prefix_hits.eng{engine_id}").set(
                pager_stats.prefix_hits)
        g("serve/blocks_free").set(pager_stats.blocks_free)
        g("serve/blocks_used").set(pager_stats.blocks_used)
        g("serve/blocks_shared").set(pager_stats.blocks_shared)
        g("serve/block_refs").set(pager_stats.block_refs)
        g("serve/cow_copies").set(pager_stats.cow_copies)
        g("serve/kv_util").set(kv_util)
        g("serve/page_occupancy").set(
            pager_stats.blocks_used / pager_stats.blocks_total
            if pager_stats.blocks_total else 0.0)
        g("serve/sharing_ratio").set(
            pager_stats.block_refs / pager_stats.blocks_used
            if pager_stats.blocks_used else 1.0)
        # persistent prefix cache: parked-block occupancy + cumulative
        # cross-request adoption wins (metrics_summary's 0%-hit-with-
        # repeats WARN reads these alongside shared_hits)
        g("serve/lru_blocks").set(pager_stats.lru_blocks)
        g("serve/prefix_hits").set(pager_stats.prefix_hits)
        g("serve/prefix_hit_tokens").set(pager_stats.prefix_hit_tokens)
        g("serve/prefix_repeats").set(pager_stats.prefix_repeats)
        g("serve/shared_hits").set(pager_stats.shared_hits)
        # cross-process tier: splices that came from the shared pool
        # rather than the in-process registry (a subset of prefix_hits)
        g("serve/pool_hits").set(getattr(pager_stats, "pool_hits", 0))
        g("serve/pool_hit_tokens").set(
            getattr(pager_stats, "pool_hit_tokens", 0))

    def serve_pool(self, pool_stats, engine_id=None):
        """Per-step cross-process KV-pool gauges (cheap sets, no event).
        ``pool_stats`` is ``DecodeEngine.pool_stats()``: cumulative
        export/fetch counters plus the current generation — gauges, not
        counters, because the engine owns the cumulative values and
        re-emits them every step (the same pattern as serve_paged)."""
        g = self.registry.gauge
        g("pool/gen").set(pool_stats.get("gen", 0))
        g("pool/exports").set(pool_stats.get("exports", 0))
        g("pool/export_errors").set(pool_stats.get("export_errors", 0))
        g("pool/fetches").set(pool_stats.get("fetches", 0))
        g("pool/fetch_hits").set(pool_stats.get("fetch_hits", 0))
        g("pool/fetch_misses").set(pool_stats.get("fetch_misses", 0))
        g("pool/adopted_blocks").set(pool_stats.get("adopted_blocks", 0))
        g("pool/adopted_tokens").set(pool_stats.get("adopted_tokens", 0))
        g("pool/pending_exports").set(pool_stats.get("pending_exports", 0))
        if engine_id is not None:
            g(f"pool/fetch_hits.eng{engine_id}").set(
                pool_stats.get("fetch_hits", 0))

    def serve_admitted(self, ttft_s: float, bucket: int, prefill_s: float):
        """A request's prefill folded into a free slot; its first token is
        out. ttft_s spans submit -> first token (queue wait included)."""
        self.registry.counter("serve/admissions").inc()
        self.registry.histogram("serve/ttft_s").observe(ttft_s)
        self.registry.histogram("serve/prefill_s").observe(prefill_s)
        self.emit("serve_admit", ttft_s=ttft_s, bucket=bucket,
                  prefill_s=prefill_s)

    def serve_step(self, dur_s: float, live: int, queue_depth: int,
                   engine_id=None):
        """One decode step over all live slots: per-token latency is
        dur_s (the whole batch advances one token per step)."""
        self.registry.counter("serve/decode_steps").inc()
        self.registry.counter("serve/tokens").inc(live)
        self.registry.gauge("serve/live_slots").set(live)
        self.registry.gauge("serve/queue_depth").set(queue_depth)
        self.registry.histogram("serve/step_s").observe(dur_s)
        # goodput: the decode executable ran full-shape over max_slots rows
        # (HFU) while only `live` of them carried requests (MFU) — the
        # ledger scales model FLOPs by the live fraction; decode tokens are
        # GENERATED tokens, the serving-throughput figure
        now = time.perf_counter()
        self.goodput.dispatch("serve", (engine_id, "decode", None),
                              now - dur_s, now, tokens=live,
                              generated=True)

    def serve_spec_step(self, dur_s: float, drafted: int, accepted: int,
                        emitted: int, width: int, drafter: str,
                        live: int = 0, queue_depth: int = 0,
                        accepted_per_step=None, hit_rate=None,
                        engine_id=None):
        """One speculative verify dispatch for one slot: ``drafted`` tokens
        proposed, ``accepted`` of them agreed with the verifier, and
        ``emitted`` tokens actually advanced the request (accepted + the
        bonus token, clipped by eos/budget). Goodput accounting is the
        multi-token mirror of serve_step: the verify executable ran
        ``width`` positions (HFU bills all of them), but only ``emitted``
        tokens are model progress — the ledger's tokens/registered-tokens
        scaling attributes exactly the accepted fraction to MFU, so
        rejected-draft FLOPs can never inflate utilization, and
        serve/tokens_per_s_chip counts ACCEPTED tokens only."""
        c = self.registry.counter
        c("serve/spec_steps").inc()
        c("serve/tokens").inc(emitted)
        if drafted:
            c("serve/spec_drafted").inc(drafted)
            c(f"serve/spec_drafted.{drafter}").inc(drafted)
        if accepted:
            c("serve/spec_accepted").inc(accepted)
            c(f"serve/spec_accepted.{drafter}").inc(accepted)
        c(f"serve/spec_emitted.{drafter}").inc(emitted)
        g = self.registry.gauge
        g("serve/live_slots").set(live)
        g("serve/queue_depth").set(queue_depth)
        if accepted_per_step is not None:
            g("serve/spec_accepted_per_step").set(accepted_per_step)
        if hit_rate is not None:
            g("serve/spec_draft_hit_rate").set(hit_rate)
        self.registry.histogram("serve/spec_step_s").observe(dur_s)
        now = time.perf_counter()
        self.goodput.dispatch("serve", (engine_id, "verify", width),
                              now - dur_s, now, tokens=emitted,
                              generated=True)

    def serve_spec(self, drafter: str, drafted: int, accepted: int,
                   emitted: int, trace_id=None):
        """A speculative request finished: its whole-lifetime draft ledger
        as one event (per-step figures live in the counters above)."""
        fields = dict(drafter=drafter, drafted=int(drafted),
                      accepted=int(accepted), emitted=int(emitted))
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_spec", **fields)

    def serve_prefill_step(self, dur_s: float, bucket, tokens: int,
                           engine_id=None):
        """One prefill execution (a chunk iteration, or a monolithic
        bucketed prefill): productive time + FLOPs for the goodput ledger;
        ``tokens`` is the VALID token count this call carried (a padded
        chunk tail is hardware work but not model work)."""
        now = time.perf_counter()
        self.goodput.dispatch("serve", (engine_id, "prefill", bucket),
                              now - dur_s, now, tokens=tokens)

    def serve_sched(self, t0: float, t1: float):
        """One whole scheduler iteration (``DecodeEngine.step()``) as a
        perf_counter bracket: the executable calls inside it classify as
        productive/compile, the remainder is engine host overhead — which
        makes a serving burst's timeline gap-free."""
        self.goodput.add("overhead", t0, t1)

    def serve_done(self, n_tokens: int, total_s: float, status: str):
        """A request left its slot (stop condition hit)."""
        self.registry.counter("serve/completions").inc()
        self.registry.histogram("serve/request_s").observe(total_s)
        self.registry.histogram("serve/request_tokens").observe(n_tokens)
        self.emit("serve_done", tokens=n_tokens, total_s=total_s,
                  status=status)

    # ------------------------------------------ integration: serving guardrails

    def serve_expired(self, where: str, preemptions: int = 0,
                      tokens: int = 0, trace_id=None):
        """A request's deadline passed at a step boundary (terminal status
        "expired"); ``where`` names the state it died in (queue / prefill /
        decode / drain). ``preemptions > 0`` on expiry events is the
        pool-thrash signature metrics_summary WARNs on: requests are
        losing their deadline budget to eviction-and-recompute churn, so
        raise kv_blocks or lower deadlines. ``trace_id``: the expired
        request's own trace."""
        self.registry.counter("serve/expired").inc()
        fields = dict(where=where, preemptions=int(preemptions),
                      tokens=int(tokens))
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_expire", **fields)

    def serve_cancelled(self, where: str, trace_id=None):
        """engine.cancel() terminalized a request (queue / prefill /
        decode); its slot and blocks are already released."""
        self.registry.counter("serve/cancelled").inc()
        fields = dict(where=where)
        if trace_id:
            fields["trace"] = trace_id
        self.emit("serve_cancel", **fields)

    def serve_drain_begin(self, live: int, queued: int,
                          grace_s: Optional[float]):
        """The engine's door closed (begin_drain): ``live`` slots get the
        grace budget, ``queued`` requests bounce as rejected_draining."""
        self.emit("serve_drain_begin", live=int(live), queued=int(queued),
                  grace_s=grace_s)

    def serve_drain_end(self, wall_s: float):
        """Drain complete: nothing in flight. serve/drained counts drain
        OPERATIONS (per-request outcomes live in completions / expired /
        rejected_draining)."""
        self.registry.counter("serve/drained").inc()
        self.emit("serve_drain_end", wall_s=wall_s)

    def serve_hang(self, kind: str, bucket, elapsed_s: float, hang_s: float,
                   engine_id=None, trace_ids=()):
        """The dispatch watchdog caught a decode/chunk call exceeding
        PADDLE_SERVE_HANG_S — emitted FROM the watchdog thread while the
        dispatch is still stuck, so the evidence outlives a wedged
        process. ``trace_ids``: the live requests' traces (escalated past
        head sampling by the caller)."""
        self.registry.counter("serve/hang_warns").inc()
        self.emit("serve_hang", path=kind, bucket=bucket,
                  elapsed_s=elapsed_s, hang_s=hang_s, engine=engine_id,
                  traces=list(trace_ids))

    # ---------------------------------------------- integration: fleet router

    def route_placed(self, engine, affinity: bool):
        """The router placed one request: ``affinity`` means its prompt's
        first-block digest matched a key the chosen engine advertised
        (cache-aware hit); otherwise it spilled to least-loaded. Counters
        only — placement happens per request, an event per call would
        swamp the sink."""
        if affinity:
            self.registry.counter("route/affinity_hits").inc()
        else:
            self.registry.counter("route/spills").inc()

    def route_reject(self, why: str):
        """No engine could take the request (every door draining/stale or
        the fleet is empty) — the router's own saturation signal."""
        self.registry.counter("route/rejected").inc()
        self.emit("route_reject", why=why)

    def route_queued(self, depth: int):
        """Every live door was at capacity, so the router parked the
        request in its bounded admission queue instead of rejecting it;
        ``depth`` is the queue depth after the push. Saturation that
        resolves itself shows up here, not in route/rejected."""
        self.registry.counter("route/queued").inc()
        self.registry.gauge("route/queue_depth").set(int(depth))

    def route_requeue(self, request_id, from_engine, to_engine,
                      why: str, trace_id=None):
        """A request moved to a different engine (its first engine died or
        bounced it draining). The engine-side id dedup makes this
        idempotent, so a requeue is bookkeeping, never a duplicate
        generation."""
        self.registry.counter("route/requeues").inc()
        fields = dict(request=str(request_id), src=str(from_engine),
                      dst=str(to_engine), why=why)
        if trace_id:
            fields["trace"] = trace_id
        self.emit("route_requeue", **fields)

    def route_eject(self, engine, why: str):
        """The router declared one engine dead (stale heartbeat, transport
        failure past retry, or chaos kill) and removed it from placement;
        only a strictly NEWER incarnation re-admits that name."""
        self.registry.counter("route/ejections").inc()
        self.emit("route_eject", engine=str(engine), why=why)

    def route_state(self, doors, counters):
        """Periodic router fleet view (per-engine door state + router
        counters) — tools/fleet_top.py's router panel renders the latest
        of these."""
        self.emit("route_state", doors=doors, counters=dict(counters))

    # -------------------------------------------------- integration: profiler

    def stage_event(self, name: str, start: float, end: float, kind: str):
        """Mirror of profiler stage/user ranges into the sink, so one JSONL
        carries both the always-on metrics and any traced windows."""
        self.emit("stage", name=name, stage_kind=kind,
                  start=start, end=end, dur_s=end - start)

    # --------------------------------------------------------- memory census

    def memory_census(self, top: int = 10) -> dict:
        census = live_array_census(top)
        self.registry.gauge("memory/live_arrays").set(census["count"])
        self.registry.gauge("memory/live_bytes").set(census["total_bytes"])
        self.emit("census", **census)
        return census

    # ---------------------------------------------------------- post-mortems

    def dump(self, path: Optional[str] = None,
             exc: Optional[BaseException] = None) -> str:
        if path is None:
            base = self.sink.path if self.sink is not None \
                else f"monitor_{os.getpid()}.jsonl"
            root, _ = os.path.splitext(base)
            path = root + ".flight.json"
        snap = self._emit_counters()
        self.flush()
        # rank 0 with the fleet plane up: the crash report says what the
        # FLEET looked like, not just the dying rank
        fleet = None
        try:
            from . import collector as _collector
            fleet = _collector.fleet_state()
        except Exception:
            pass
        # span-tracer context: the dump names the trace(s) to open, and a
        # crash force-samples everything in flight so they exist on disk
        trace_info = None
        tracer = _trace_mod._active
        if tracer is not None:
            if exc is not None:
                tracer.escalate(reason="crash")
            trace_info = tracer.snapshot_info()
            tracer.flush()
        return self.flight.dump(path, registry_snapshot=snap, exc=exc,
                                fleet=fleet, trace=trace_info)

    def on_crash(self, exc: BaseException):
        # one dump per exception object: TrainStep.__call__ raising inside
        # Model.fit would otherwise dump twice on the same failure. The mark
        # lives ON the exception (not an id() set: a collected exception's id
        # gets reused, which would silently suppress a later real dump)
        if getattr(exc, "_paddle_monitor_dumped", False):
            return
        try:
            exc._paddle_monitor_dumped = True
        except Exception:
            pass  # unmarkable exception: accept a possible double dump
        try:
            path = self.dump(exc=exc)
            self.emit("crash", dump=path, exc_type=type(exc).__name__)
            self.flush()
        except Exception:
            pass  # post-mortem tooling must never mask the real exception


# ------------------------------------------------------------------ module API


def enable(path: Optional[str] = None, *, warn_after: Optional[int] = None,
           flush_every: int = 64, ring: int = 256,
           fleet=None, trace=None) -> Monitor:
    """Turn the monitor on. ``path`` is the JSONL sink file (None: flight
    recorder only); in multi-process runs each process writes
    ``path.procN`` (see sink.resolve_sink_path). Idempotent-safe: enabling
    while enabled closes the previous session first.

    ``fleet`` starts the online fleet-telemetry plane (monitor/collector.py):
    True derives the rank-0 stream path from ``path`` (``run.jsonl`` ->
    ``run.fleet.jsonl``), a string is the explicit stream path. Default None
    follows the ``PADDLE_MONITOR_FLEET`` env.

    ``trace`` starts the span tracer (monitor/trace.py) the same way: True
    derives ``run.trace.jsonl`` from ``path`` (per-process suffix applies —
    every rank traces its own requests/steps), a string is the explicit
    path; default None follows ``PADDLE_TRACE``; sampling follows
    ``PADDLE_TRACE_SAMPLE``."""
    global _active
    with _lock:
        if _active is not None:
            _teardown_locked()
        mon = Monitor(path, warn_after=warn_after, flush_every=flush_every,
                      ring=ring)
        _install_hooks(mon)
        _goodput_mod._set_active(mon.goodput)
        _active = mon
    if fleet is None:
        v = os.environ.get("PADDLE_MONITOR_FLEET")
        # explicit falsy values DISABLE (an operator's FLEET=0 must not
        # start the plane with a stream file literally named "0")
        fleet = None if not v or v.lower() in ("0", "false", "no", "off") \
            else v
    if fleet:
        from . import collector as _collector
        _collector.start(
            registry=mon.registry, emit=mon.emit,
            fleet_path=_collector.resolve_fleet_path(
                fleet if isinstance(fleet, str) else None, path))
    if trace is None:
        v = os.environ.get("PADDLE_TRACE")
        trace = None if not v or v.lower() in ("0", "false", "no", "off") \
            else v
    if trace:
        if isinstance(trace, str) and trace.lower() not in ("1", "true",
                                                            "yes", "on"):
            tpath = trace
        else:
            base = path or f"monitor_{os.getpid()}.jsonl"
            root, _ = os.path.splitext(base)
            tpath = root + ".trace.jsonl"
        tracer = _trace_mod.enable(tpath)
        tracer._via_monitor = True   # disable() tears it down with us
    return mon


def _install_hooks(mon: Monitor):
    from ..core import dispatch
    dispatch.set_monitor_hooks(mon.op_hook, mon.op_compile_hook)


def _teardown_locked():
    global _active
    mon, _active = _active, None
    _goodput_mod._set_active(None)
    from ..core import dispatch
    dispatch.set_monitor_hooks(None, None)
    from . import collector as _collector
    if mon is not None and _collector.get_active() is not None:
        # only the plane over THIS session's registry dies with it
        if _collector.get_active().publisher.registry is mon.registry:
            _collector.stop()
    tracer = _trace_mod.get()
    if mon is not None and tracer is not None \
            and getattr(tracer, "_via_monitor", False):
        # a tracer the user enabled directly outlives the monitor session
        _trace_mod.disable()
    if mon is not None:
        mon.close()


def disable():
    """Flush + close the sink, uninstall dispatch hooks."""
    with _lock:
        _teardown_locked()


def enabled() -> bool:
    return _active is not None


def get() -> Optional[Monitor]:
    return _active


def emit(kind: str, **fields):
    mon = _active
    if mon is not None:
        mon.emit(kind, **fields)


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write the flight-recorder post-mortem JSON now (enabled monitor only)."""
    mon = _active
    if mon is None:
        return None
    return mon.dump(path)


def counter(name: str) -> Optional[Counter]:
    mon = _active
    return mon.registry.counter(name) if mon is not None else None


def gauge(name: str) -> Optional[Gauge]:
    mon = _active
    return mon.registry.gauge(name) if mon is not None else None


def histogram(name: str) -> Optional[Histogram]:
    mon = _active
    return mon.registry.histogram(name) if mon is not None else None


def snapshot() -> Optional[dict]:
    mon = _active
    if mon is None:
        return None
    try:
        mon.goodput.refresh()   # idle/fraction current as of THIS snapshot
    except Exception:
        pass
    return mon.registry.snapshot()


def fleet_state() -> Optional[dict]:
    """Rank 0's latest aggregated fleet record when the collector plane is
    up (monitor/collector.py); None on other ranks or when inactive."""
    from . import collector as _collector
    return _collector.fleet_state()


def prom_render(source=None) -> str:
    """Prometheus text-format view of monitor metrics (monitor/prom.py).

    ``source=None`` renders the LIVE registry of the enabled monitor (plus
    the latest fleet record when the collector plane is up — per-rank
    values gain ``rank`` labels); pass a registry ``snapshot()`` dict or a
    fleet record to render those instead. Empty string when nothing is
    enabled."""
    if source is None:
        mon = _active
        fleet = fleet_state()
        if fleet is not None:
            return _prom.render(fleet)
        if mon is None:
            return ""
        # a scrape must see current goodput/idle figures, not the state as
        # of the last hook event
        try:
            mon.goodput.refresh()
        except Exception:
            pass
        source = mon.registry.snapshot()
    return _prom.render(source)


def on_crash(exc: BaseException):
    """Integration-point crash hook (TrainStep/Model.fit except blocks)."""
    mon = _active
    if mon is not None:
        mon.on_crash(exc)


def _maybe_enable_from_env():
    """PADDLE_MONITOR=<path|1> opt-in, read once at import. A bad value
    (unparsable warn_after, unwritable path) must degrade to a warning —
    telemetry can never be the reason `import paddle_tpu` fails."""
    v = os.environ.get("PADDLE_MONITOR")
    if not v:
        return
    path = v if v.lower() not in ("1", "true", "yes", "on") \
        else f"monitor_{os.getpid()}.jsonl"
    try:
        wa = os.environ.get("PADDLE_MONITOR_WARN_AFTER")
        enable(path, warn_after=int(wa) if wa else None)
    except Exception as e:
        warnings.warn(f"PADDLE_MONITOR={v!r}: could not enable the monitor "
                      f"({type(e).__name__}: {e}); continuing without "
                      f"telemetry", RuntimeWarning)


@atexit.register
def _atexit_flush():
    mon = _active
    if mon is not None:
        try:
            mon.close()
        except Exception:
            pass
