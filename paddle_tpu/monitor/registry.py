"""Metric primitives + registry for paddle_tpu.monitor.

Reference analog: the reference framework's statistics/benchmark layer
(python/paddle/profiler/utils.py benchmark, fluid monitor counters); shape
borrowed from the Prometheus client model (Counter/Gauge/Histogram) because
that is what production telemetry pipelines ingest.

Thread-safety: DeviceLoader's producer thread and the training thread both
touch these, so every mutation takes the registry lock. The lock is only ever
contended while the monitor is ENABLED — disabled hot paths never reach here
(they guard on ``monitor._active is None``).
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry"]

# decade buckets in seconds: dispatch latencies live in 1e-5..1e0, compile
# times in 1e-1..1e2 — one fixed scale covers both without configuration
_DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0)


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "_n", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._n = 0
        self._lock = lock

    def inc(self, n: int = 1):
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        return self._n

    def snapshot(self):
        return self._n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._v = 0.0
        self._lock = lock

    def set(self, v: float):
        with self._lock:
            self._v = float(v)

    def add(self, v: float):
        with self._lock:
            self._v += float(v)

    @property
    def value(self) -> float:
        return self._v

    def snapshot(self):
        return self._v


class Histogram:
    """Fixed-bucket distribution (count/sum/min/max + cumulative buckets).

    Buckets are upper bounds in the observed unit (seconds for latencies);
    an implicit +inf bucket catches the tail. `quantile(q)` interpolates the
    bucket boundaries — coarse, but stable and allocation-free on observe.
    """

    __slots__ = ("name", "buckets", "_counts", "_n", "_sum", "_min", "_max",
                 "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._n = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = lock

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._n += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def avg(self) -> float:
        return self._sum / self._n if self._n else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-boundary estimate of the q-quantile (0 < q <= 1)."""
        if not self._n:
            return 0.0
        target = q * self._n
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= target:
                if i < len(self.buckets):
                    return self.buckets[i]
                return self._max
        return self._max

    def snapshot(self) -> dict:
        return {"count": self._n, "sum": self._sum, "avg": self.avg,
                "min": self._min if self._n else 0.0,
                "max": self._max if self._n else 0.0,
                "p50": self.quantile(0.5), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class Registry:
    """Name -> primitive store. Creation is idempotent; asking for an
    existing name with a different type raises (silent shadowing would
    corrupt the exported snapshot)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, self._lock, *args)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._get_or_create(name, Histogram,
                                *((buckets,) if buckets else ()))
        if buckets is not None and h.buckets != tuple(sorted(buckets)):
            # same no-silent-shadowing rule as a type mismatch: observations
            # landing in someone else's bucket scale corrupt quantile()
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, not {tuple(sorted(buckets))}")
        return h

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        JSON-ready, stable key order."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    @staticmethod
    def delta(prev: Optional[dict], cur: dict) -> dict:
        """Changed-metrics view of ``cur`` vs a previous ``snapshot()``.

        Values stay CUMULATIVE (the fleet collector's loss-tolerant wire
        format: a missed blob costs nothing because the next one carries
        absolute values again); only UNCHANGED keys are dropped. Histograms
        compare on observation count — a summary whose count moved is
        re-sent whole. ``prev=None`` returns ``cur`` unchanged (the full
        first publish of an incarnation)."""
        if prev is None:
            return cur
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for kind in ("counters", "gauges"):
            pk = prev.get(kind) or {}
            for name, v in (cur.get(kind) or {}).items():
                if pk.get(name) != v:
                    out[kind][name] = v
        ph = prev.get("histograms") or {}
        for name, h in (cur.get("histograms") or {}).items():
            if (ph.get(name) or {}).get("count") != h.get("count"):
                out["histograms"][name] = h
        return out

    def remove_prefix(self, prefix: str):
        """Unregister every metric whose name starts with ``prefix`` — for
        metrics scoped to an object that no longer exists (e.g. per-bucket
        executable gauges after the executables are dropped), where a stale
        value would misattribute live state."""
        with self._lock:
            for name in [n for n in self._metrics if n.startswith(prefix)]:
                del self._metrics[name]

    def reset(self):
        with self._lock:
            self._metrics.clear()
