"""Memory accounting — per-executable HBM estimates and a live-array census.

On TPU the second silent throughput killer (after recompiles) is HBM
pressure: an OOM surfaces as an opaque allocator error long after the
decision that caused it. XLA already knows the answer at compile time —
``compiled.memory_analysis()`` reports argument/output/temp/generated-code
bytes per executable — and the runtime knows the live-array population.
This module turns both into numbers you can watch BEFORE the OOM.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["executable_memory_stats", "live_array_census"]

_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
           "temp_size_in_bytes", "alias_size_in_bytes",
           "generated_code_size_in_bytes")


def executable_memory_stats(compiled) -> Optional[dict]:
    """HBM footprint estimate of one compiled executable.

    Returns ``{"argument_bytes", "output_bytes", "temp_bytes",
    "alias_bytes", "generated_code_bytes", "total_bytes"}`` or None when the
    backend does not expose memory analysis (older plugin runtimes).
    ``total_bytes`` is the peak-resident estimate: args + outputs + temps
    minus aliased (donated) buffers, which XLA reuses in place.
    """
    analyze = getattr(compiled, "memory_analysis", None)
    if analyze is None:
        return None
    try:
        ma = analyze()
    except Exception:
        return None
    if ma is None:
        return None
    vals = {}
    for f in _FIELDS:
        vals[f.replace("_size_in_bytes", "_bytes")] = int(getattr(ma, f, 0))
    vals["total_bytes"] = (vals["argument_bytes"] + vals["output_bytes"]
                           + vals["temp_bytes"] - vals["alias_bytes"])
    return vals


def live_array_census(top: int = 10) -> dict:
    """Snapshot of every live jax.Array on the host process.

    Returns ``{"count", "total_bytes", "top": [{"shape", "dtype", "nbytes",
    "sharded"}...]}`` sorted by size. This is the "why is HBM full" helper:
    run it when memory gauges trend up and the biggest residents name
    themselves.
    """
    import jax

    arrs = [a for a in jax.live_arrays() if not getattr(a, "is_deleted",
                                                        lambda: False)()]
    sized = []
    total = 0
    for a in arrs:
        try:
            nb = int(a.nbytes)
        except Exception:
            continue
        total += nb
        sized.append((nb, a))
    sized.sort(key=lambda t: t[0], reverse=True)
    top_list = []
    for nb, a in sized[:max(int(top), 0)]:
        try:
            sharded = len(a.sharding.device_set) > 1
        except Exception:
            sharded = False
        top_list.append({"shape": list(a.shape), "dtype": str(a.dtype),
                         "nbytes": nb, "sharded": sharded})
    return {"count": len(sized), "total_bytes": total, "top": top_list}
