"""Model-health plane — the fifth observability layer: watch the *numbers*.

The other four layers watch time and throughput (metrics → fleet → traces →
goodput/MFU); nothing watched the values flowing through the step. A NaN'd
gradient, a silently diverging rank, or a loss spike stays invisible until
the run is garbage — the failure class the reference framework dedicates
``check_nan_inf`` / ``paddle.amp.debugging`` (TensorCheckerConfig) to, and
the *detection* half of the MegaScale detect/eject/rollover doctrine whose
*response* half (checkpoint commits, fleet tripwires) earlier PRs built.

Four channels, all compiled INTO the existing executables (flags are data,
not shape — the zero-steady-state-recompile gates hold with health ON; the
disabled path stays the single ``monitor._active is None`` check):

* **numerics tripwires** — a packed per-leaf-group isfinite/overflow stat
  block rides ``TrainStep``'s compiled outputs (forward loss + grads).  The
  host pulls it only every ``PADDLE_HEALTH_SAMPLE`` steps (one sync per
  sample, not per step); a trip escalates the step's trace, WARNs naming
  the offending leaf groups, runs an eager follow-up sweep over the live
  params for exact leaf attribution, dumps the flight ring, and advances
  ``health/*`` counters.
* **per-layer tensor stats** — grad-norm, activation RMS (collected through
  the existing ``core/remat.py`` checkpoint-name tags attn_qkv /
  attn_context / attn_out / mlp_hidden), and update-to-weight ratio per
  leaf group, gauged on the sample cadence so ``fleet_top``/prom see
  layer-resolved health.  Activation taps are SUSPENDED inside
  ``jax.lax.scan`` bodies and ``jax.checkpoint`` (remat) regions — a value
  recorded there is an inner-trace tracer that cannot legally escape to the
  step's outputs — so activation RMS covers the discrete-block non-remat
  path; grad/update/digest stats work everywhere.
* **loss-spike detector** — rolling median/MAD window over the (sampled)
  loss with quarantine semantics: the spike value never enters the window.
  An opt-in ``rollback_on_spike`` hook (``hapi.callbacks.AutoCheckpoint``,
  or ``TrainStep.rollback_last_commit`` in a raw loop) restores the last
  snapshot committed BEFORE the spike step.
* **cross-rank weight-divergence digests** — a fixed-pseudo-random-
  projection digest of params and grads computed in-executable (Rademacher
  probes hashed elementwise from the flat index, salted per leaf and
  probe — partition-invariant under TP/ZeRO, nothing materialized), gauged
  per rank and published through the fleet collector; the aggregator flags
  a rank whose *weights* — not just step counts — diverged.

Env surface: ``PADDLE_HEALTH=0`` opts a monitor session out;
``PADDLE_HEALTH_SAMPLE`` (default 16) is the host sampling cadence;
``PADDLE_HEALTH_OVERFLOW`` (default 1e8) the |grad| overflow threshold;
``PADDLE_HEALTH_DIGEST`` (default 2) the probe count (0 disables digests);
``PADDLE_HEALTH_SPIKE_WINDOW``/``_K``/``_MIN`` tune the spike detector;
``PADDLE_HEALTH_FAULT`` is the chaos seam (mirror of PADDLE_CKPT_FAULT /
PADDLE_SERVE_FAULT): ``nan@param:N[:leaf]`` poisons a parameter with NaN
before call N, ``scale@param:N[:factor]`` multiplies one to plant a loss
spike — host-side, deterministic, parsed once.
"""
from __future__ import annotations

import math
import os
import threading
import warnings
from collections import deque
from typing import Optional

import numpy as np

__all__ = ["HealthPlane", "CompiledHealth", "SpikeDetector", "FaultPlan",
           "collect_taps", "suspend_taps", "active_taps", "probe_salt",
           "DIGEST_STEP_GAUGE", "DIGEST_PREFIX"]

# gauge names the fleet aggregator keys its cross-rank comparison on
DIGEST_STEP_GAUGE = "health/digest_step"
DIGEST_PREFIX = "health/digest/"


def probe_salt(leaf_j: int, probe_d: int) -> int:
    """The 32-bit salt seeding leaf ``j``'s probe-``d`` Rademacher vector
    (shared with tests' eager oracle: the digest contract is that the
    compiled sharded computation reproduces exactly this keying)."""
    return (0x5EED ^ (leaf_j * 0x9E3779B9) ^ (probe_d * 0x85EBCA6B)) \
        & 0xFFFFFFFF


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------------ activation taps
#
# core/remat.py's tag_array calls active_taps() at TRACE time: when a
# collector is open (TrainStep building with health on) each named
# activation contributes (sum of squares, element count) so the harvested
# RMS rides the executable's outputs. Thread-local: tracing happens on the
# calling thread; a serving engine tracing concurrently never sees a train
# step's collector.

_tls = threading.local()


class _TapCollector:
    def __init__(self):
        self.sumsq = {}
        self.count = {}

    def record(self, name: str, x) -> None:
        import jax.numpy as jnp
        xf = x.astype(jnp.float32)
        self.sumsq[name] = self.sumsq.get(name, 0.0) + jnp.sum(xf * xf)
        self.count[name] = self.count.get(name, 0) + int(x.size)

    def harvest(self) -> dict:
        """{name: rms} as traced scalars (empty when nothing tapped)."""
        import jax.numpy as jnp
        return {n: jnp.sqrt(self.sumsq[n] / max(self.count[n], 1))
                for n in self.sumsq}


def active_taps() -> Optional[_TapCollector]:
    if getattr(_tls, "suspended", 0):
        return None
    return getattr(_tls, "taps", None)


class collect_taps:
    """Context manager: collect named-activation stats while tracing."""

    def __enter__(self) -> _TapCollector:
        self._prev = getattr(_tls, "taps", None)
        _tls.taps = _TapCollector()
        return _tls.taps

    def __exit__(self, *exc):
        _tls.taps = self._prev
        return False


class suspend_taps:
    """Pause tap collection inside scan bodies / jax.checkpoint regions,
    where recorded values would be inner-trace tracers that cannot escape
    to the step's outputs (re-entrant)."""

    def __enter__(self):
        _tls.suspended = getattr(_tls, "suspended", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.suspended -= 1
        return False


# ------------------------------------------------------------- leaf grouping


def leaf_groups(names):
    """Group trainable-leaf names by module (name minus its last component:
    ``h.3.attn.qkv_proj.weight`` → ``h.3.attn.qkv_proj``) — fine enough to
    name the offending layer, coarse enough that the packed stat block
    stays a few hundred floats. Returns (group names, per-leaf index)."""
    groups, group_of, index = [], [], {}
    for n in names:
        g = n.rsplit(".", 1)[0] if "." in n else n
        if g not in index:
            index[g] = len(groups)
            groups.append(g)
        group_of.append(index[g])
    return groups, group_of


# --------------------------------------------------------- compiled builders


class CompiledHealth:
    """The trace-time half: jnp builders TrainStep._build calls while
    constructing the step function. Everything returned is fixed-shape
    ([G,3] grad stats, [G,2] update/weight, [2] loss, [D] digests) — the
    sampling cadence and every threshold stay host-side data, so health
    never adds a shape bucket."""

    def __init__(self, plane: "HealthPlane", names):
        self.plane = plane
        self.groups, self.group_of = leaf_groups(names)
        self.names = list(names)
        self.n_probes = plane.digest_probes

    # stat layout columns (host side indexes by these)
    GRAD_NONFINITE, GRAD_MAXABS, GRAD_SUMSQ = 0, 1, 2

    def grad_stats(self, grads):
        """[G, 3] per leaf group: (nonfinite count, max |finite|, finite
        sum-of-squares). NaN/Inf are excluded from the max/sumsq columns so
        the overflow and norm figures stay meaningful on a tripped step."""
        import jax.numpy as jnp
        G = len(self.groups)
        nf = [jnp.float32(0.0)] * G
        mx = [jnp.float32(0.0)] * G
        ss = [jnp.float32(0.0)] * G
        for g, gi in zip(grads, self.group_of):
            gf = g.astype(jnp.float32)
            fin = jnp.isfinite(gf)
            a = jnp.where(fin, jnp.abs(gf), 0.0)
            nf[gi] = nf[gi] + (jnp.float32(gf.size) -
                               jnp.sum(fin).astype(jnp.float32))
            mx[gi] = jnp.maximum(mx[gi], jnp.max(a) if gf.size else 0.0)
            ss[gi] = ss[gi] + jnp.sum(a * a)
        return jnp.stack([jnp.stack(nf), jnp.stack(mx), jnp.stack(ss)],
                         axis=1)

    def ratio_stats(self, new_upd, upd_in):
        """[G, 2] per leaf group: (sum |Δw|², sum |w|²) in fp32 — the
        update-to-weight ratio ‖Δw‖/‖w‖ is the classic LR-sanity figure."""
        import jax.numpy as jnp
        G = len(self.groups)
        du = [jnp.float32(0.0)] * G
        w = [jnp.float32(0.0)] * G
        for nu, u, gi in zip(new_upd, upd_in, self.group_of):
            d = (nu.astype(jnp.float32) - u.astype(jnp.float32))
            du[gi] = du[gi] + jnp.sum(d * d)
            uf = u.astype(jnp.float32)
            w[gi] = w[gi] + jnp.sum(uf * uf)
        return jnp.stack([jnp.stack(du), jnp.stack(w)], axis=1)

    def loss_stats(self, loss):
        """[2]: (nonfinite flag, |loss|) — the forward tripwire."""
        import jax.numpy as jnp
        lf = loss.astype(jnp.float32)
        return jnp.stack([1.0 - jnp.isfinite(lf).astype(jnp.float32),
                          jnp.abs(jnp.where(jnp.isfinite(lf), lf, 0.0))])

    def digest(self, leaves):
        """[D] fixed-pseudo-random-projection digest: per probe d, the sum
        over leaves j of ⟨leaf_j, r(j, d)⟩ where r is a ±1 Rademacher vector
        derived ELEMENTWISE from the flat index by an integer hash (murmur3
        finalizer) salted with (leaf, probe). Elementwise-in-the-index is
        the load-bearing property: each device hashes exactly the indices of
        the shard it holds, so the digest of a sharded leaf is bitwise the
        digest of the gathered global leaf — partition-INVARIANT under
        TP/ZeRO, which ``jax.random.*`` inside an SPMD program is not (the
        partitioner may split a threefry counter computation and change the
        bits; jax_threefry_partitionable defaults off). Nothing is
        materialized between steps and every rank derives identical probes,
        so two ranks holding bitwise-equal weights produce bitwise-equal
        digests and the fleet aggregator can flag the rank whose weights
        forked."""
        import jax
        import jax.numpy as jnp

        def probe(n, j, d):
            i = jax.lax.iota(jnp.uint32, n)
            x = i ^ jnp.uint32(probe_salt(j, d))
            x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
            x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
            x = x ^ (x >> 16)
            return 1.0 - 2.0 * (x & 1).astype(jnp.float32)

        out = []
        for d in range(self.n_probes):
            acc = jnp.float32(0.0)
            for j, x in enumerate(leaves):
                r = probe(int(x.size), j, d)
                acc = acc + jnp.vdot(x.astype(jnp.float32).reshape(-1), r)
            out.append(acc)
        return jnp.stack(out)

    def pack(self, loss, grads, new_upd, upd_in, act):
        """The health output pytree riding the step's loss_out dict."""
        out = {"loss2": self.loss_stats(loss),
               "grad": self.grad_stats(grads),
               "ratio": self.ratio_stats(new_upd, upd_in)}
        if self.n_probes > 0:
            out["pdig"] = self.digest(new_upd)
            out["gdig"] = self.digest(grads)
        if act:
            out["act"] = act
        return out


# ------------------------------------------------------------ spike detector


class SpikeDetector:
    """Rolling median/MAD outlier test with quarantine semantics: a value
    flagged as a spike is NEVER appended to the window (one bad step must
    not drag the baseline toward itself, and a rollback replaying the same
    region must re-trip deterministically)."""

    def __init__(self, window: int = 32, k: float = 10.0, min_fill: int = 8):
        self.window = max(int(window), 4)
        self.k = float(k)
        self.min_fill = max(int(min_fill), 2)
        self.vals = deque(maxlen=self.window)

    def observe(self, loss: float) -> Optional[dict]:
        """Feed one loss; returns a spike-info dict or None."""
        loss = float(loss)
        if not math.isfinite(loss):
            return {"loss": loss, "median": None, "mad": None,
                    "nonfinite": True}
        if len(self.vals) >= self.min_fill:
            s = sorted(self.vals)
            med = s[len(s) // 2]
            mad = sorted(abs(v - med) for v in s)[len(s) // 2]
            floor = 1e-8 * max(abs(med), 1.0)
            if abs(loss - med) > self.k * max(mad, floor):
                return {"loss": loss, "median": med, "mad": mad,
                        "nonfinite": False}
        self.vals.append(loss)
        return None

    def reset(self):
        self.vals.clear()


# ----------------------------------------------------------------- chaos seam


class FaultPlan:
    """PADDLE_HEALTH_FAULT: deterministic host-side numerics faults, the
    mirror of PADDLE_CKPT_FAULT / PADDLE_SERVE_FAULT. Schedule syntax
    ``<action>@<site>:<nth>[:<arg>]``, comma-separated:

    * ``nan@param:N[:leaf]``  — before TrainStep call N (1-based), write a
      NaN into element 0 of the first trainable param (or the first whose
      name contains ``leaf``). The fast path re-adopts the replaced array,
      so the poison flows through the compiled step like any real flip.
    * ``scale@param:N[:factor]`` — multiply that param by ``factor``
      (default 64): a finite perturbation that plants a loss SPIKE without
      tripping the NaN channel.

    Inputs are integer token ids here, so the seam poisons parameters —
    the realistic entry point for a numerics fault (bad HBM bit, optimizer
    bug, torn restore) anyway."""

    def __init__(self, entries):
        self.entries = entries          # [(action, nth, arg)]
        self.calls = 0
        self.fired = []

    @classmethod
    def parse(cls, spec: Optional[str]) -> Optional["FaultPlan"]:
        if not spec:
            return None
        entries = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                action, rest = part.split("@", 1)
                site, nth, *arg = rest.split(":")
                if site != "param" or action not in ("nan", "scale"):
                    raise ValueError(part)
                entries.append((action, int(nth), arg[0] if arg else None))
            except (ValueError, IndexError):
                warnings.warn(f"PADDLE_HEALTH_FAULT: unparsable entry "
                              f"{part!r} (want <nan|scale>@param:<nth>"
                              f"[:<arg>]); ignoring it", RuntimeWarning)
        return cls(entries) if entries else None

    def maybe_fire(self, named_params, emit=None) -> None:
        """Called once per TrainStep call with [(name, Parameter)]."""
        self.calls += 1
        for action, nth, arg in self.entries:
            if nth != self.calls:
                continue
            self._fire(action, arg, named_params, emit)

    def _fire(self, action, arg, named_params, emit):
        import jax
        target = None
        for n, p in named_params:
            if not p.trainable:
                continue
            if action == "nan" and arg and arg not in n:
                continue
            target = (n, p)
            break
        if target is None:
            return
        n, p = target
        arr = np.asarray(jax.device_get(p.value()))
        if action == "nan":
            arr = arr.copy()
            arr.flat[0] = np.nan
        else:
            arr = arr * np.asarray(float(arg) if arg else 64.0,
                                   dtype=arr.dtype)
        sharding = getattr(p._data, "sharding", None)
        p._data = jax.device_put(arr, sharding) if sharding is not None \
            else jax.device_put(arr)
        self.fired.append((self.calls, action, n))
        if emit is not None:
            emit("health_fault", call=self.calls, action=action, leaf=n)


# ------------------------------------------------------------------ the plane


class HealthPlane:
    """One monitor session's health state: config, spike detector, trip
    bookkeeping, and the host half of the sampled check. Created by
    ``Monitor.__init__`` — it rides every session unless PADDLE_HEALTH
    opts out — and consulted by TrainStep at build time (compiled half)
    and on the sample cadence (host half)."""

    def __init__(self, monitor):
        self.monitor = monitor
        v = os.environ.get("PADDLE_HEALTH", "")
        self.enabled = not (v and v.lower() in ("0", "false", "no", "off"))
        self.sample_every = max(_env_int("PADDLE_HEALTH_SAMPLE", 16), 1)
        self.overflow = _env_float("PADDLE_HEALTH_OVERFLOW", 1e8)
        self.digest_probes = max(_env_int("PADDLE_HEALTH_DIGEST", 2), 0)
        self.spike = SpikeDetector(
            window=_env_int("PADDLE_HEALTH_SPIKE_WINDOW", 32),
            k=_env_float("PADDLE_HEALTH_SPIKE_K", 10.0),
            min_fill=_env_int("PADDLE_HEALTH_SPIKE_MIN", 8))
        self.fault = FaultPlan.parse(os.environ.get("PADDLE_HEALTH_FAULT"))
        self.rollback_hook = None     # callable(step, info) — opt-in
        self.nan_trips = 0
        self.overflow_trips = 0
        self.spikes = 0
        self._dumps = 0
        self._max_dumps = 3

    # ---------------------------------------------------------- compile side

    def compiled_spec(self, names) -> Optional[CompiledHealth]:
        """The builder TrainStep._build asks for; None keeps the program
        byte-for-byte what it always was."""
        if not self.enabled:
            return None
        return CompiledHealth(self, names)

    # ------------------------------------------------------------- host side

    def should_sample(self, step_n: int) -> bool:
        return self.enabled and step_n % self.sample_every == 0

    def on_sample(self, spec: CompiledHealth, step_n: int, loss_val: float,
                  payload: dict, named_params=None, trace=None) -> dict:
        """One sampled host check: gauges, tripwires, spike feed, digest
        publication. ``payload`` is the device pytree pulled to numpy by
        the caller (the sample's one sync). Returns {"nan":…, "overflow":…,
        "spike":…} describing what tripped."""
        reg = self.monitor.registry
        g = reg.gauge
        groups = spec.groups
        grad = np.asarray(payload["grad"], np.float64)
        ratio = np.asarray(payload["ratio"], np.float64)
        loss2 = np.asarray(payload["loss2"], np.float64)

        g("health/sample_every").set(self.sample_every)
        g("health/groups").set(len(groups))
        g("health/loss").set(float(loss_val)
                             if math.isfinite(float(loss_val)) else -1.0)
        for i, name in enumerate(groups):
            g(f"health/grad_norm.{name}").set(math.sqrt(max(grad[i, 2], 0)))
            g(f"health/grad_max.{name}").set(grad[i, 1])
            wn = math.sqrt(max(ratio[i, 1], 0.0))
            un = math.sqrt(max(ratio[i, 0], 0.0))
            g(f"health/update_ratio.{name}").set(un / wn if wn > 0 else 0.0)
        for name, rms in (payload.get("act") or {}).items():
            g(f"health/act_rms.{name}").set(float(np.asarray(rms)))
        if "pdig" in payload:
            g(DIGEST_STEP_GAUGE).set(step_n)
            for d, v in enumerate(np.asarray(payload["pdig"], np.float64)):
                g(f"{DIGEST_PREFIX}p{d}").set(float(v))
            for d, v in enumerate(np.asarray(payload["gdig"], np.float64)):
                g(f"{DIGEST_PREFIX}g{d}").set(float(v))

        nan_groups = [groups[i] for i in np.nonzero(grad[:, 0] > 0)[0]]
        loss_bad = loss2[0] > 0
        over_groups = [groups[i]
                       for i in np.nonzero(grad[:, 1] > self.overflow)[0]]
        out = {"nan": None, "overflow": None, "spike": None}
        if nan_groups or loss_bad:
            out["nan"] = self._trip_nan(step_n, nan_groups, loss_bad,
                                        loss_val, named_params, trace)
        elif over_groups:
            out["overflow"] = self._trip_overflow(step_n, over_groups,
                                                  float(grad[:, 1].max()),
                                                  trace)
        if not loss_bad:
            sp = self.spike.observe(loss_val)
            if sp is not None:
                out["spike"] = self.spike_tripped(step_n, sp,
                                                  source="train_step",
                                                  trace=trace)
        return out

    # ------------------------------------------------------------- tripwires

    def sweep_leaves(self, named_params, limit: int = 8):
        """Eager follow-up sweep for EXACT attribution: which live leaves
        hold non-finite values right now. The compiled flags name the leaf
        GROUP cheaply every sample; this names the leaves, paid only on a
        trip. (Under a compiled-in GradScaler the update was skipped and
        params stay clean — then the grad-stat groups are the attribution
        and this sweep correctly reports no poisoned weights.)"""
        bad = []
        for n, p in named_params or ():
            try:
                a = np.asarray(p.value(), np.float32)
            except Exception:
                continue
            k = int(np.size(a) - np.count_nonzero(np.isfinite(a)))
            if k:
                bad.append({"leaf": n, "nonfinite": k})
                if len(bad) >= limit:
                    break
        return bad

    def _flight_dump(self):
        if self._dumps >= self._max_dumps:
            return None
        self._dumps += 1
        try:
            return self.monitor.dump()
        except Exception:
            return None

    def _escalate(self, reason: str):
        from . import trace as _trace_mod
        tracer = _trace_mod._active
        if tracer is not None:
            tracer.escalate(reason=reason)
            return tracer.current_trace_id()
        return None

    def _trip_nan(self, step_n, groups, loss_bad, loss_val, named_params,
                  trace):
        self.nan_trips += 1
        mon = self.monitor
        mon.registry.counter("health/nan_trips").inc()
        for name in groups:
            mon.registry.counter(f"health/nan_trips.{name}").inc()
        tid = trace or self._escalate("health_nan")
        leaves = self.sweep_leaves(named_params)
        dump = self._flight_dump()
        info = dict(step=step_n, groups=groups, loss_nonfinite=bool(loss_bad),
                    loss=float(loss_val), leaves=leaves, dump=dump)
        mon.emit("health_nan", **({"trace": tid, **info} if tid else info))
        where = ", ".join(groups) if groups else "forward loss"
        warnings.warn(
            f"health: non-finite values at step {step_n} in [{where}]"
            + (f"; poisoned leaves: "
               f"{[b['leaf'] for b in leaves]}" if leaves else "")
            + (f" [trace {tid}]" if tid else "")
            + " — see the health_nan event / flight dump for the sweep",
            RuntimeWarning, stacklevel=3)
        return info

    def _trip_overflow(self, step_n, groups, max_abs, trace):
        self.overflow_trips += 1
        mon = self.monitor
        mon.registry.counter("health/overflow_trips").inc()
        tid = trace or self._escalate("health_overflow")
        info = dict(step=step_n, groups=groups, max_abs=max_abs,
                    threshold=self.overflow)
        mon.emit("health_overflow",
                 **({"trace": tid, **info} if tid else info))
        warnings.warn(
            f"health: |grad| {max_abs:.3e} exceeds the overflow threshold "
            f"{self.overflow:.1e} at step {step_n} in [{', '.join(groups)}]"
            + (f" [trace {tid}]" if tid else ""),
            RuntimeWarning, stacklevel=3)
        return info

    def spike_tripped(self, step_n, sp: dict, source: str, trace=None):
        """A loss spike was detected (by the sampled channel or a fit-loop
        feed). Emits + counts, then runs the opt-in rollback hook."""
        self.spikes += 1
        mon = self.monitor
        mon.registry.counter("health/spikes").inc()
        tid = trace or self._escalate("health_spike")
        info = dict(step=step_n, source=source, **sp)
        mon.emit("health_spike", **({"trace": tid, **info} if tid else info))
        med = sp.get("median")
        warnings.warn(
            f"health: loss spike at step {step_n}: {sp['loss']:.6g}"
            + (f" vs rolling median {med:.6g} (mad {sp['mad']:.3g})"
               if med is not None else " (non-finite)")
            + (f" [trace {tid}]" if tid else ""),
            RuntimeWarning, stacklevel=3)
        hook, self_info = self.rollback_hook, info
        if hook is not None:
            try:
                res = hook(step_n, info)
            except Exception as e:
                warnings.warn(f"health: rollback_on_spike hook failed "
                              f"({type(e).__name__}: {e}); training "
                              f"continues un-rolled-back", RuntimeWarning)
                res = None
            if res is not None:
                mon.registry.counter("health/rollbacks").inc()
                mon.emit("health_rollback", spike_step=step_n,
                         restored_step=res.get("step")
                         if isinstance(res, dict) else None)
                self.spike.reset()
                self_info["rollback"] = res if isinstance(res, dict) \
                    else {"restored": True}
        return self_info

    def scaler_outcome(self, found_inf: bool, scale: float):
        """amp.GradScaler feed: the loss-scale trajectory next to the trip
        timeline is how the summary separates 'scaler doing its job'
        (trips with skipped updates) from 'update unprotected'."""
        reg = self.monitor.registry
        reg.gauge("health/loss_scale").set(float(scale))
        if found_inf:
            reg.counter("health/found_inf").inc()
