"""paddle._C_ops — best-effort shim for code that calls the raw C-op bindings.

Reference analog: python/paddle/_C_ops.py re-exports the generated Python-C
functions (eager_api_* from libpaddle); user/framework code occasionally calls
them directly (`paddle._C_ops.matmul(x, y, False, False)`).

Here ops are registry entries, not C bindings, so this module forwards
attribute lookups to the public functional surface by name. Signatures match
the KEYWORD forms; positional attr-packs from the legacy C interface differ
per op, so unknown names raise with the nearest matches listed rather than
guessing.
"""
from __future__ import annotations

import difflib
from typing import Any

__all__: list = []


def _candidates():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.dispatch import _REGISTRY
    return paddle, F, _REGISTRY


def __getattr__(name: str) -> Any:
    if name.startswith("__"):
        raise AttributeError(name)
    paddle, F, registry = _candidates()
    target = getattr(paddle, name, None) or getattr(F, name, None)
    if target is not None and callable(target):
        globals()[name] = target   # memoize: later accesses skip __getattr__
        return target
    if name.startswith("final_state_"):  # legacy generated-name prefix
        target = __getattr__(name[len("final_state_"):])
        globals()[name] = target
        return target
    pool = sorted(set(dir(paddle)) | set(dir(F)))
    near = difflib.get_close_matches(name, pool, n=3)
    raise AttributeError(
        f"_C_ops.{name}: no matching op in the functional surface"
        + (f"; close matches: {near}" if near else ""))
