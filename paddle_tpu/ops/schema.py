"""Declarative op schema → generated bindings (the L3 codegen layer).

Reference analog: phi/api/yaml/ops.yaml + the generator scripts
(phi/api/yaml/generator/api_base.py:1187, eager_gen.py, python_c_gen.py):
there, a YAML schema generates the C++ API, autograd nodes and Python-C
bindings at build time. Here the schema is a Python table and "generation"
happens at import: each OpSpec produces a registered dispatch op, a module
function with a real signature + docstring, and (optionally) a Tensor method —
one declaration, every binding, exactly the codegen contract, minus the
build-time C++ because the kernels are jnp lowerings.

`emit_stubs()` writes the generated surface as a .pyi for tooling — the
artifact the reference emits as generated source files.
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ._helpers import _op

__all__ = ["OpSpec", "OP_SCHEMA", "generate_bindings", "emit_stubs"]


@dataclass
class OpSpec:
    name: str
    fwd: Callable                       # jnp-level kernel
    args: Sequence[str] = ("x",)        # tensor arguments, in order
    attrs: Dict[str, object] = field(default_factory=dict)  # name -> default
    doc: str = ""
    tensor_method: bool = False         # also patch onto Tensor
    nondiff_inputs: Sequence[int] = ()


# ---------------------------------------------------------------- the schema
# (ops.yaml rows; kernels are jnp lowerings instead of PD_REGISTER_KERNELs)

OP_SCHEMA: List[OpSpec] = [
    OpSpec("nextafter", jnp.nextafter, args=("x", "y"),
           doc="Next representable value after x towards y.",
           tensor_method=True),
    OpSpec("i0", lambda x: jnp.i0(x),
           doc="Modified Bessel function of the first kind, order 0.",
           tensor_method=True),
    OpSpec("sinc", jnp.sinc, doc="Normalized sinc.", tensor_method=True),
    OpSpec("xlogy", lambda x, y: jnp.where(
        x == 0, jnp.zeros_like(jnp.asarray(y, dtype=jnp.result_type(x, y))),
        x * jnp.log(y)), args=("x", "y"),
        doc="x * log(y), zero where x == 0.", tensor_method=True),
    OpSpec("signbit", jnp.signbit, doc="True where the sign bit is set.",
           tensor_method=True),
    OpSpec("trapezoid",
           lambda y, x=None, *, dx=1.0, axis=-1: jnp.trapezoid(
               y, x=x, dx=dx, axis=axis) if x is not None
           else jnp.trapezoid(y, dx=dx, axis=axis),
           args=("y", "x"), attrs={"dx": 1.0, "axis": -1},
           doc="Trapezoidal-rule integral along an axis."),
    OpSpec("vander",
           lambda x, *, n=None, increasing=False: jnp.vander(
               x, N=n, increasing=increasing),
           attrs={"n": None, "increasing": False},
           doc="Vandermonde matrix."),
    OpSpec("polar", lambda abs, angle: abs * jnp.exp(1j * angle),
           args=("abs", "angle"),
           doc="Complex tensor from magnitude and phase."),
    OpSpec("ldexp", lambda x, y: x * (2.0 ** y), args=("x", "y"),
           doc="x * 2**y.", tensor_method=True),
    OpSpec("hypot_generated", jnp.hypot, args=("x", "y"),
           doc="sqrt(x^2 + y^2) (generated-schema variant)."),
]


def _build_api(spec: OpSpec) -> Callable:
    register_op(spec.name, spec.fwd, nondiff_inputs=spec.nondiff_inputs)
    n_tensors = len(spec.args)
    attr_names = list(spec.attrs)

    def api(*call_args, **kwargs):
        tensors = list(call_args[:n_tensors])
        # positional args beyond the tensor slots map onto attrs in order
        # (paddle-style positional attr calls must not be silently dropped)
        extras = call_args[n_tensors:]
        if len(extras) > len(attr_names):
            raise TypeError(f"{spec.name}() takes at most "
                            f"{n_tensors + len(attr_names)} positional "
                            f"arguments ({len(call_args)} given)")
        attrs = dict(spec.attrs)
        for k, v in zip(attr_names, extras):
            attrs[k] = v
        # fill tensor args passed by keyword; drop trailing optional Nones
        for i, a in enumerate(spec.args):
            if i >= len(tensors):
                tensors.append(kwargs.pop(a, None))
        while tensors and tensors[-1] is None:
            tensors.pop()
        for k in attr_names:
            if k in kwargs:
                attrs[k] = kwargs.pop(k)
        kwargs.pop("name", None)
        if kwargs:
            raise TypeError(f"{spec.name}() got unexpected kwargs "
                            f"{sorted(kwargs)}")
        return _op(spec.name, *tensors, **attrs)

    params = [inspect.Parameter(a, inspect.Parameter.POSITIONAL_OR_KEYWORD,
                                default=None if i > 0 else
                                inspect.Parameter.empty)
              for i, a in enumerate(spec.args)]
    params += [inspect.Parameter(k, inspect.Parameter.KEYWORD_ONLY, default=v)
               for k, v in spec.attrs.items()]
    params.append(inspect.Parameter("name", inspect.Parameter.KEYWORD_ONLY,
                                    default=None))
    api.__signature__ = inspect.Signature(params)
    api.__name__ = spec.name
    api.__qualname__ = spec.name
    api.__doc__ = (spec.doc or spec.name) + \
        "\n\n(Generated from paddle_tpu.ops.schema — declarative op registry.)"
    return api


def generate_bindings(namespace: dict):
    """Generate every schema op into `namespace` (+ Tensor methods)."""
    generated = []
    for spec in OP_SCHEMA:
        api = _build_api(spec)
        namespace[spec.name] = api
        if spec.tensor_method and not hasattr(Tensor, spec.name):
            setattr(Tensor, spec.name, api)
        generated.append(spec.name)
    return generated


def emit_stubs(path: Optional[str] = None) -> str:
    """Write the generated API surface as a .pyi stub (the build artifact)."""
    lines = ["# AUTO-GENERATED from paddle_tpu.ops.schema — do not edit.",
             "from typing import Any", ""]
    for spec in OP_SCHEMA:
        sig_args = list(spec.args) + \
            [f"{k}={v!r}" for k, v in spec.attrs.items()] + ["name=None"]
        lines.append(f"def {spec.name}({', '.join(sig_args)}) -> Any: ...")
    text = "\n".join(lines) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
