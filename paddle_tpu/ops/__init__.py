"""Op corpus + Tensor method monkey-patching.

Reference analog: `python/paddle/tensor/__init__.py`'s monkey_patch of math methods onto
the Tensor type (the reference generates these from YAML; here they're direct bindings to
the dispatchable ops).
"""
from __future__ import annotations

from builtins import any as _any, all as _all, slice as _builtin_slice

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, register_op
from ..core.tensor import Tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from . import linalg  # namespace: paddle_tpu.linalg
from . import creation as _creation
from . import math as _math
from . import manipulation as _manip
from . import logic as _logic

from .math import (add, subtract, multiply, divide, matmul, pow as _pow,
                   remainder, floor_divide, neg, abs as _abs)
from .logic import (equal, not_equal, less_than, less_equal, greater_than,
                    greater_equal)
from .manipulation import cast as _cast


def _op(name, *tensors, **attrs):
    return apply_op(name, tensors, attrs)


# ---------------------------------------------------------------- indexing


def _split_index(index):
    """Split a python index expression into a static spec + dynamic tensor operands."""
    if not isinstance(index, tuple):
        index = (index,)
    spec = []
    tensor_args = []
    for item in index:
        if isinstance(item, Tensor):
            if item.dtype == jnp.bool_:
                # boolean mask → dynamic shape: handled by caller eagerly
                spec.append(("mask", len(tensor_args)))
            else:
                spec.append(("tensor", len(tensor_args)))
            tensor_args.append(item)
        elif isinstance(item, np.ndarray):
            t = Tensor(item)
            spec.append(("tensor", len(tensor_args)))
            tensor_args.append(t)
        elif isinstance(item, _builtin_slice):
            spec.append(("slice", (item.start, item.stop, item.step)))
        elif item is None:
            spec.append(("newaxis", None))
        elif item is Ellipsis:
            spec.append(("ellipsis", None))
        elif isinstance(item, (list,)):
            arr = np.asarray(item)
            if arr.dtype == np.bool_:
                t = Tensor(arr)
                spec.append(("mask", len(tensor_args)))
                tensor_args.append(t)
            else:
                t = Tensor(arr.astype(np.int32))
                spec.append(("tensor", len(tensor_args)))
                tensor_args.append(t)
        else:
            spec.append(("int", int(item)))
    return tuple(spec), tensor_args


def _materialize_index(spec, arrays):
    idx = []
    for kind, payload in spec:
        if kind == "tensor" or kind == "mask":
            idx.append(arrays[payload])
        elif kind == "slice":
            idx.append(_builtin_slice(*payload))
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        else:
            idx.append(payload)
    return tuple(idx)


def _getitem_fwd(x, *index_arrays, spec=()):
    return x[_materialize_index(spec, index_arrays)]


register_op("getitem", _getitem_fwd)


def _tensor_getitem(self, index):
    spec, tensor_args = _split_index(index)
    if _any(k == "mask" for k, _ in spec):
        # dynamic-shape boolean indexing: eager numpy materialization
        np_idx = _materialize_index(spec, [np.asarray(t.numpy()) for t in tensor_args])
        return Tensor(self.numpy()[np_idx])
    return _op("getitem", self, *tensor_args, spec=spec)


def _setitem_fwd(x, *args, spec=(), n_idx=0):
    index_arrays = args[:n_idx]
    value = args[n_idx]
    idx = _materialize_index(spec, index_arrays)
    return x.at[idx].set(value.astype(x.dtype) if hasattr(value, "astype") else value)


register_op("setitem", _setitem_fwd)


def _tensor_setitem(self, index, value):
    spec, tensor_args = _split_index(index)
    if not isinstance(value, Tensor):
        value = Tensor(np.asarray(value), dtype=self.dtype)
    if _any(k == "mask" for k, _ in spec):
        np_idx = _materialize_index(spec, [np.asarray(t.numpy()) for t in tensor_args])
        arr = np.asarray(self.numpy())
        arr[np_idx] = np.asarray(value.numpy())
        new = Tensor(arr, dtype=self.dtype)
        self._data = new.value()
        self._version += 1
        return
    out = _op("setitem", _snapshot(self), *tensor_args, value, spec=spec,
              n_idx=len(tensor_args))
    # in-place semantics with autograd rewiring (reference: inplace ops bump version)
    _rewire_inplace(self, out)


# ---------------------------------------------------------------- dunders & methods


def _install_tensor_methods():
    T = Tensor
    T.__getitem__ = _tensor_getitem
    T.__setitem__ = _tensor_setitem
    T.__add__ = lambda s, o: add(s, o)
    T.__radd__ = lambda s, o: add(s, o)
    T.__sub__ = lambda s, o: subtract(s, o)
    T.__rsub__ = lambda s, o: subtract(_ensure(o, s), s)
    T.__mul__ = lambda s, o: multiply(s, o)
    T.__rmul__ = lambda s, o: multiply(s, o)
    T.__truediv__ = lambda s, o: divide(s, o)
    T.__rtruediv__ = lambda s, o: divide(_ensure(o, s), s)
    T.__floordiv__ = lambda s, o: floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: floor_divide(_ensure(o, s), s)
    T.__mod__ = lambda s, o: remainder(s, o)
    T.__rmod__ = lambda s, o: remainder(_ensure(o, s), s)
    T.__pow__ = lambda s, o: _pow(s, o)
    T.__rpow__ = lambda s, o: _pow(_ensure(o, s), s)
    T.__matmul__ = lambda s, o: matmul(s, o)
    T.__rmatmul__ = lambda s, o: matmul(_ensure(o, s), s)
    T.__neg__ = lambda s: neg(s)
    T.__abs__ = lambda s: _abs(s)
    T.__invert__ = lambda s: _logic.logical_not(s)
    T.__eq__ = lambda s, o: equal(s, o)
    T.__ne__ = lambda s, o: not_equal(s, o)
    T.__lt__ = lambda s, o: less_than(s, o)
    T.__le__ = lambda s, o: less_equal(s, o)
    T.__gt__ = lambda s, o: greater_than(s, o)
    T.__ge__ = lambda s, o: greater_equal(s, o)
    T.__hash__ = lambda s: id(s)
    T.__and__ = lambda s, o: _logic.logical_and(s, o) if s.dtype == jnp.bool_ else _math.bitwise_and(s, o)
    T.__or__ = lambda s, o: _logic.logical_or(s, o) if s.dtype == jnp.bool_ else _math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: _logic.logical_xor(s, o) if s.dtype == jnp.bool_ else _math.bitwise_xor(s, o)

    # named methods (subset of the ~300 the reference patches; grown over time)
    method_table = {}
    for mod in (_math, _manip, _logic, _creation, linalg):
        for nm in getattr(mod, "__all__", []):
            fn = getattr(mod, nm, None)
            if callable(fn):
                method_table.setdefault(nm, fn)
    skip = {"to_tensor", "is_tensor", "meshgrid", "zeros", "ones", "full", "empty",
            "arange", "linspace", "logspace", "eye", "rand", "randn", "randint",
            "uniform", "normal", "randperm", "one_hot", "einsum", "multi_dot",
            "broadcast_tensors"}
    for nm, fn in method_table.items():
        if nm in skip or hasattr(T, nm):
            continue
        setattr(T, nm, fn)

    T.astype = lambda s, dtype: _cast(s, dtype)
    T.cast = lambda s, dtype: _cast(s, dtype)
    T.mm = lambda s, o: matmul(s, o)
    T.dot = _math.dot
    T.add_ = _make_inplace(add)
    T.subtract_ = _make_inplace(subtract)
    T.multiply_ = _make_inplace(multiply)
    T.divide_ = _make_inplace(divide)
    T.scale_ = _make_inplace(_math.scale)
    T.clip_ = _make_inplace(_math.clip)
    T.exp_ = _make_inplace(_math.exp)
    T.sqrt_ = _make_inplace(_math.sqrt)
    T.rsqrt_ = _make_inplace(_math.rsqrt)
    T.floor_ = _make_inplace(_math.floor)
    T.ceil_ = _make_inplace(_math.ceil)
    T.round_ = _make_inplace(_math.round)
    T.reciprocal_ = _make_inplace(_math.reciprocal)
    T.fill_ = _fill_
    T.zero_ = lambda s: _fill_(s, 0)
    T.uniform_ = _uniform_
    T.normal_ = _normal_


def _ensure(o, like):
    if isinstance(o, Tensor):
        return o
    return Tensor(jnp.asarray(o))


def _snapshot(t):
    """Shallow autograd snapshot so an in-place op consumes the OLD node, not a
    self-loop (the new node must not list its own output tensor as an input)."""
    snap = Tensor(t.value(), stop_gradient=t.stop_gradient)
    snap._grad_node = t._grad_node
    snap._out_index = t._out_index
    return snap


def _rewire_inplace(self, out):
    self._data = out.value()
    self._grad_node = out._grad_node
    self._out_index = out._out_index
    self._version += 1
    return self


def _make_inplace(fn):
    def inplace(self, *args, **kwargs):
        out = fn(_snapshot(self), *args, **kwargs)
        return _rewire_inplace(self, out)
    return inplace


def _fill_(self, value):
    self._data = jnp.full(tuple(self.shape), value, self.dtype)
    self._version += 1
    return self


def _uniform_(self, min=-1.0, max=1.0, seed=0):
    from ..core import random as rng
    import jax
    self._data = jax.random.uniform(rng.split_key(), tuple(self.shape), self.dtype,
                                    minval=float(min), maxval=float(max))
    self._version += 1
    return self


def _normal_(self, mean=0.0, std=1.0):
    from ..core import random as rng
    import jax
    self._data = (jax.random.normal(rng.split_key(), tuple(self.shape), self.dtype)
                  * std + mean)
    self._version += 1
    return self


_install_tensor_methods()

# L3 codegen layer: declarative schema -> generated bindings (ops/schema.py)
from . import schema as _schema  # noqa: E402
_GENERATED_OPS = _schema.generate_bindings(globals())
