"""Shape/layout/indexing ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import register_op
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from builtins import range as _range, slice as _pyslice, sum as _sum

from ._helpers import _op, static_int_list

__all__ = [
    "reshape", "transpose", "squeeze", "unsqueeze", "flatten", "cast",
    "concat", "stack", "split", "chunk", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_tensors", "flip", "rot90", "roll",
    "gather", "gather_nd", "scatter", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "masked_select", "masked_fill",
    "where", "take_along_axis", "put_along_axis", "slice", "strided_slice",
    "unbind", "unstack", "repeat_interleave", "t", "moveaxis", "as_strided",
    "topk", "sort", "argsort", "argmax", "argmin", "unique", "unique_consecutive",
    "nonzero", "one_hot", "pad", "crop", "shard_index", "tensordot",
    "searchsorted", "bucketize", "mode", "kthvalue", "tolist", "atleast_1d",
    "atleast_2d", "atleast_3d", "view", "view_as", "as_complex", "as_real",
]


def cast(x, dtype):
    dt = convert_dtype(dtype)
    return _op("cast", x, dtype=str(np.dtype(dt)))


register_op("cast", lambda x, dtype="float32": x.astype(dtype))


def reshape(x, shape, name=None):
    return _op("reshape", x, shape=static_int_list(shape))


register_op("reshape", lambda x, shape=(): jnp.reshape(x, shape))

view = reshape


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm, name=None):
    return _op("transpose", x, perm=static_int_list(perm))


register_op("transpose", lambda x, perm=(): jnp.transpose(x, perm))


def t(x, name=None):
    if x.ndim < 2:
        return _op("clone", x)
    return _op("t2", x)


register_op("t2", lambda x: jnp.swapaxes(x, -1, -2))


def moveaxis(x, source, destination, name=None):
    return _op("moveaxis", x, source=static_int_list(source),
               destination=static_int_list(destination))


register_op("moveaxis", lambda x, source=(), destination=():
            jnp.moveaxis(x, source, destination))


def squeeze(x, axis=None, name=None):
    if axis is None:
        return _op("squeeze_all", x)
    ax = static_int_list(axis)
    ax = tuple(a for a in ax if x.shape[a] == 1)
    return _op("squeeze", x, axis=ax)


register_op("squeeze_all", lambda x: jnp.squeeze(x))
register_op("squeeze", lambda x, axis=(): jnp.squeeze(x, axis) if axis else x)


def unsqueeze(x, axis, name=None):
    return _op("unsqueeze", x, axis=static_int_list(axis))


register_op("unsqueeze", lambda x, axis=(): jnp.expand_dims(x, axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _op("flatten", x, start_axis=int(start_axis), stop_axis=int(stop_axis))


def _flatten_fwd(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    s = start_axis % nd
    e = stop_axis % nd
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return x.reshape(new_shape)


register_op("flatten", _flatten_fwd)


def concat(x, axis=0, name=None):
    tensors = list(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _op("concat", *tensors, axis=int(axis))


register_op("concat", lambda *xs, axis=0: jnp.concatenate(xs, axis=axis))


def stack(x, axis=0, name=None):
    return _op("stack", *list(x), axis=int(axis))


register_op("stack", lambda *xs, axis=0: jnp.stack(xs, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(
                f"split: axis {axis} size {dim} is not divisible by num {n} "
                f"(pass explicit section sizes for uneven splits)")
        sizes = [dim // n] * n
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        n_neg = [i for i, s in enumerate(sizes) if s < 0]
        if n_neg:
            rest = dim - _sum(s for s in sizes if s >= 0)
            sizes[n_neg[0]] = rest
    outs = _op("split", x, sizes=tuple(sizes), axis=axis)
    return list(outs)


def _split_fwd(x, sizes=(), axis=0):
    indices = np.cumsum(sizes[:-1]).tolist()
    return tuple(jnp.split(x, indices, axis=axis))


register_op("split", _split_fwd)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    outs = _op("unbind", x, axis=int(axis), n=int(n))
    return list(outs)


def _unbind_fwd(x, axis=0, n=1):
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis))


register_op("unbind", _unbind_fwd)

unstack = unbind


def tile(x, repeat_times, name=None):
    return _op("tile", x, reps=static_int_list(repeat_times))


register_op("tile", lambda x, reps=(): jnp.tile(x, reps))


def expand(x, shape, name=None):
    tgt = static_int_list(shape)
    tgt = tuple(x.shape[i - (len(tgt) - x.ndim)] if s == -1 else s
                for i, s in enumerate(tgt))
    return _op("broadcast_to", x, shape=tgt)


def expand_as(x, y, name=None):
    return _op("broadcast_to", x, shape=tuple(y.shape))


def broadcast_to(x, shape, name=None):
    return _op("broadcast_to", x, shape=static_int_list(shape))


register_op("broadcast_to", lambda x, shape=(): jnp.broadcast_to(x, shape))


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [broadcast_to(t, out_shape) for t in inputs]


def flip(x, axis, name=None):
    return _op("flip", x, axis=static_int_list(axis))


register_op("flip", lambda x, axis=(): jnp.flip(x, axis))


def rot90(x, k=1, axes=(0, 1), name=None):
    return _op("rot90", x, k=int(k), axes=tuple(int(a) for a in axes))


register_op("rot90", lambda x, k=1, axes=(0, 1): jnp.rot90(x, k, axes))


def roll(x, shifts, axis=None, name=None):
    return _op("roll", x, shifts=static_int_list(shifts),
               axis=None if axis is None else static_int_list(axis))


register_op("roll", lambda x, shifts=(), axis=None: jnp.roll(x, shifts, axis))

# ------------------------------------------------------------------ gather/scatter


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _op("gather", x, index, axis=int(axis))


register_op("gather", lambda x, index, axis=0:
            jnp.take(x, index.reshape(-1) if index.ndim > 1 else index, axis=axis),
            nondiff_inputs=(1,))


def gather_nd(x, index, name=None):
    return _op("gather_nd", x, index)


def _gather_nd_fwd(x, index):
    idx_depth = index.shape[-1]
    batch_shape = index.shape[:-1]
    flat_idx = index.reshape(-1, idx_depth)
    parts = tuple(flat_idx[:, i] for i in range(idx_depth))
    out = x[parts]
    return out.reshape(batch_shape + x.shape[idx_depth:])


register_op("gather_nd", _gather_nd_fwd, nondiff_inputs=(1,))


def scatter(x, index, updates, overwrite=True, name=None):
    return _op("scatter", x, index, updates, overwrite=bool(overwrite))


def _scatter_fwd(x, index, updates, overwrite=True):
    idx = index.reshape(-1)
    if overwrite:
        return x.at[idx].set(updates)
    zeroed = x.at[idx].set(jnp.zeros_like(updates))
    return zeroed.at[idx].add(updates)


register_op("scatter", _scatter_fwd, nondiff_inputs=(1,))


def scatter_nd_add(x, index, updates, name=None):
    return _op("scatter_nd_add", x, index, updates)


def _scatter_nd_add_fwd(x, index, updates):
    idx_depth = index.shape[-1]
    flat_idx = index.reshape(-1, idx_depth)
    flat_updates = updates.reshape((flat_idx.shape[0],) + x.shape[idx_depth:])
    parts = tuple(flat_idx[:, i] for i in range(idx_depth))
    return x.at[parts].add(flat_updates)


register_op("scatter_nd_add", _scatter_nd_add_fwd, nondiff_inputs=(1,))


def scatter_nd(index, updates, shape, name=None):
    zeros_t = Tensor(jnp.zeros(static_int_list(shape),
                     updates.dtype if not isinstance(updates, Tensor) else updates.dtype))
    return scatter_nd_add(zeros_t, index, updates)


def index_select(x, index, axis=0, name=None):
    return _op("index_select", x, index, axis=int(axis))


register_op("index_select", lambda x, index, axis=0:
            jnp.take(x, index.reshape(-1), axis=axis), nondiff_inputs=(1,))


def index_sample(x, index, name=None):
    return _op("index_sample", x, index)


register_op("index_sample", lambda x, index:
            jnp.take_along_axis(x, index.astype(jnp.int32), axis=1), nondiff_inputs=(1,))


def index_add(x, index, axis, value, name=None):
    return _op("index_add", x, index, value, axis=int(axis))


def _index_add_fwd(x, index, value, axis=0):
    moved = jnp.moveaxis(x, axis, 0)
    v = jnp.moveaxis(value, axis, 0)
    out = moved.at[index.reshape(-1)].add(v)
    return jnp.moveaxis(out, 0, axis)


register_op("index_add", _index_add_fwd, nondiff_inputs=(1,))


def index_put(x, indices, value, accumulate=False, name=None):
    idx_tensors = list(indices)
    return _op("index_put", x, *idx_tensors, value, accumulate=bool(accumulate),
               n_idx=len(idx_tensors))


def _index_put_fwd(x, *args, accumulate=False, n_idx=1):
    idx = tuple(args[:n_idx])
    value = args[n_idx]
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


register_op("index_put", _index_put_fwd)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (cannot appear inside traced programs)
    arr = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    m = mask.value() if isinstance(mask, Tensor) else jnp.asarray(mask)
    return Tensor(arr[np.asarray(m)])


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return _op("masked_fill_t", x, mask, value)
    return _op("masked_fill", x, mask, value=float(value))


register_op("masked_fill", lambda x, mask, value=0.0:
            jnp.where(mask, jnp.asarray(value, x.dtype), x))
register_op("masked_fill_t", lambda x, mask, value:
            jnp.where(mask, value.astype(x.dtype), x))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _op("where", condition, x, y)


register_op("where", lambda c, x, y: jnp.where(c, x, y))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return _op("take_along_axis", arr, indices, axis=int(axis))


register_op("take_along_axis", lambda x, idx, axis=0:
            jnp.take_along_axis(x, idx, axis=axis), nondiff_inputs=(1,))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    if not isinstance(values, Tensor):
        values = Tensor(jnp.full(tuple(indices.shape), values, arr.dtype))
    return _op("put_along_axis", arr, indices, values, axis=int(axis), reduce=str(reduce))


def _put_along_axis_fwd(x, idx, values, axis=0, reduce="assign"):
    v = jnp.broadcast_to(values, idx.shape).astype(x.dtype)
    if reduce == "add":
        return _scatter_along_axis(x, idx, v, axis, "add")
    if reduce == "multiply" or reduce == "mul":
        return _scatter_along_axis(x, idx, v, axis, "mul")
    return _scatter_along_axis(x, idx, v, axis, "set")


def _scatter_along_axis(x, idx, v, axis, mode):
    # build open-mesh index tuple selecting along `axis` by idx
    mesh = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    index_tuple = tuple(idx if d == axis else mesh[d] for d in range(x.ndim))
    at = x.at[index_tuple]
    return {"add": at.add, "mul": at.multiply, "set": at.set}[mode](v)


register_op("put_along_axis", _put_along_axis_fwd, nondiff_inputs=(1,))

# ------------------------------------------------------------------ slicing


def slice(input, axes, starts, ends, name=None):
    return _op("slice", input, axes=static_int_list(axes),
               starts=static_int_list(starts), ends=static_int_list(ends))


def _slice_fwd(x, axes=(), starts=(), ends=()):
    idx = [_pyslice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = _pyslice(s, e)
    return x[tuple(idx)]


register_op("slice", _slice_fwd)


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _op("strided_slice", x, axes=static_int_list(axes),
               starts=static_int_list(starts), ends=static_int_list(ends),
               strides=static_int_list(strides))


def _strided_slice_fwd(x, axes=(), starts=(), ends=(), strides=()):
    idx = [_pyslice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = _pyslice(s, e, st)
    return x[tuple(idx)]


register_op("strided_slice", _strided_slice_fwd)


def crop(x, shape=None, offsets=None, name=None):
    shape = static_int_list(shape)
    offsets = static_int_list(offsets) if offsets is not None else (0,) * len(shape)
    axes = tuple(_range(len(shape)))
    starts = offsets
    ends = tuple(o + (s if s != -1 else x.shape[i] - o)
                 for i, (o, s) in enumerate(zip(offsets, shape)))
    return slice(x, axes, starts, ends)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return _op("repeat_interleave_t", x, repeats,
                   axis=None if axis is None else int(axis),
                   total=int(repeats.numpy().sum()))
    return _op("repeat_interleave", x, repeats=int(repeats),
               axis=None if axis is None else int(axis))


register_op("repeat_interleave", lambda x, repeats=1, axis=None:
            jnp.repeat(x, repeats, axis=axis))
register_op("repeat_interleave_t", lambda x, repeats, axis=None, total=0:
            jnp.repeat(x, repeats, axis=axis, total_repeat_length=total),
            nondiff_inputs=(1,))

# ------------------------------------------------------------------ sort/search


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    vals = _op("topk_vals", x, k=int(k), axis=int(axis), largest=bool(largest))
    idx = _op("topk_idx", x, k=int(k), axis=int(axis), largest=bool(largest))
    return vals, idx


def _topk(x, k=1, axis=-1, largest=True):
    ax = axis % x.ndim
    moved = jnp.moveaxis(x, ax, -1)
    src = moved if largest else -moved
    v, i = jax.lax.top_k(src, k)
    if not largest:
        v = -v
    return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax)


register_op("topk_vals", lambda x, k=1, axis=-1, largest=True: _topk(x, k, axis, largest)[0])
register_op("topk_idx", lambda x, k=1, axis=-1, largest=True:
            _topk(x, k, axis, largest)[1].astype(jnp.int32))


def sort(x, axis=-1, descending=False, name=None):
    return _op("sort", x, axis=int(axis), descending=bool(descending))


def _sort_fwd(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


register_op("sort", _sort_fwd)


def argsort(x, axis=-1, descending=False, name=None):
    return _op("argsort", x, axis=int(axis), descending=bool(descending))


def _argsort_fwd(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis)
    out = jnp.flip(out, axis=axis) if descending else out
    return out.astype(jnp.int32)


register_op("argsort", _argsort_fwd)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _op("argmax", x, axis=None if axis is None else int(axis),
               keepdim=bool(keepdim))


register_op("argmax", lambda x, axis=None, keepdim=False:
            jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
            .astype(jnp.int32))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _op("argmin", x, axis=None if axis is None else int(axis),
               keepdim=bool(keepdim))


register_op("argmin", lambda x, axis=None, keepdim=False:
            jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
            .astype(jnp.int32))


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    ax = axis % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], arr.dtype)
    idxs = np.empty(flat.shape[0], np.int32)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        v = uniq[np.argmax(counts)]
        vals[i] = v
        idxs[i] = np.where(row == v)[0][-1]
    out_shape = moved.shape[:-1]
    vals = vals.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        vals = np.expand_dims(vals, ax)
        idxs = np.expand_dims(idxs, ax)
    return Tensor(vals), Tensor(idxs)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    ax = int(axis)
    vals = _op("kthvalue_vals", x, k=int(k), axis=ax, keepdim=bool(keepdim))
    idx = _op("kthvalue_idx", x, k=int(k), axis=ax, keepdim=bool(keepdim))
    return vals, idx


def _kthvalue(x, k=1, axis=-1, keepdim=False):
    sorted_v = jnp.sort(x, axis=axis)
    argsorted = jnp.argsort(x, axis=axis)
    v = jnp.take(sorted_v, k - 1, axis=axis)
    i = jnp.take(argsorted, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i


register_op("kthvalue_vals", lambda x, k=1, axis=-1, keepdim=False: _kthvalue(x, k, axis, keepdim)[0])
register_op("kthvalue_idx", lambda x, k=1, axis=-1, keepdim=False:
            _kthvalue(x, k, axis, keepdim)[1].astype(jnp.int32))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    return _op("searchsorted", sorted_sequence, values,
               side="right" if right else "left", out_int32=bool(out_int32))


register_op("searchsorted", lambda s, v, side="left", out_int32=False:
            jnp.searchsorted(s, v, side=side).astype(jnp.int32 if out_int32 else jnp.int32))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic output shape → eager numpy path (reference runs this on CPU too)
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    outs = [Tensor(r if i == 0 else r.astype(np.int32)) for i, r in enumerate(res)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
        ax = 0
    else:
        ax = axis
    if arr.size == 0:
        outs = [Tensor(arr)]
    else:
        take_first = np.ones(arr.shape[ax], bool)
        sl = [np.s_[:]] * arr.ndim
        sl_prev = list(sl)
        sl[ax] = np.s_[1:]
        sl_prev[ax] = np.s_[:-1]
        neq = np.any(arr[tuple(sl)] != arr[tuple(sl_prev)],
                     axis=tuple(i for i in range(arr.ndim) if i != ax)) \
            if arr.ndim > 1 else arr[1:] != arr[:-1]
        take_first[1:] = neq
        uniq = np.compress(take_first, arr, axis=ax)
        outs = [Tensor(uniq)]
        if return_inverse:
            outs.append(Tensor(np.cumsum(take_first) - 1))
        if return_counts:
            idx = np.flatnonzero(take_first)
            counts = np.diff(np.append(idx, arr.shape[ax]))
            outs.append(Tensor(counts.astype(np.int32)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def nonzero(x, as_tuple=False, name=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int32)) for i in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int32))


def one_hot(x, num_classes, name=None):
    return _op("one_hot", x, num_classes=int(num_classes))


register_op("one_hot", lambda x, num_classes=1:
            jax.nn.one_hot(x, num_classes, dtype=jnp.float32))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad_list = static_int_list(pad)
    return _op("pad", x, pad=pad_list, mode=str(mode), value=float(value),
               data_format=str(data_format))


def _pad_fwd(x, pad=(), mode="constant", value=0.0, data_format="NCHW"):
    nd = x.ndim
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pairs pad the LAST spatial dim first
        # (pad_left,pad_right = W, then pad_top,pad_bottom = H, ...)
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format in ("NCHW", "NCL", "NCDHW"):
            spatial_dims = list(range(nd - 1, nd - 1 - n_spatial, -1))
        else:
            spatial_dims = list(range(nd - 2, nd - 2 - n_spatial, -1))
        for i, d in enumerate(spatial_dims):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


register_op("pad", _pad_fwd)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _op("shard_index", input, index_num=int(index_num), nshards=int(nshards),
               shard_id=int(shard_id), ignore_value=int(ignore_value))


def _shard_index_fwd(x, index_num=1, nshards=1, shard_id=0, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


register_op("shard_index", _shard_index_fwd)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else (a,) for a in axes)
    return _op("tensordot", x, y, axes=axes if isinstance(axes, int) else tuple(axes))


register_op("tensordot", lambda x, y, axes=2:
            jnp.tensordot(x, y, axes=axes if isinstance(axes, int) else tuple(map(tuple, axes))))


def atleast_1d(*inputs, name=None):
    outs = [reshape(x, (1,)) if x.ndim == 0 else x for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for x in inputs:
        if x.ndim == 0:
            outs.append(reshape(x, (1, 1)))
        elif x.ndim == 1:
            outs.append(unsqueeze(x, 0))
        else:
            outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for x in inputs:
        y = atleast_2d(x)
        outs.append(unsqueeze(y, -1) if y.ndim == 2 else y)
    return outs[0] if len(outs) == 1 else outs


def as_complex(x, name=None):
    return _op("as_complex", x)


register_op("as_complex", lambda x: jax.lax.complex(x[..., 0], x[..., 1]))


def as_real(x, name=None):
    return _op("as_real", x)


register_op("as_real", lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1))


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x.numpy()).reshape(-1)[offset:],
        shape=tuple(shape),
        strides=tuple(s * x.numpy().dtype.itemsize for s in stride))
    return Tensor(arr.copy())


def tolist(x):
    return x.numpy().tolist()
