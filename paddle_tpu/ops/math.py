"""Math ops (reference: python/paddle/tensor/math.py, ops.yaml math entries).

Every op is one jax-traceable forward; gradients come from the dispatch layer's
jit(vjp(fwd)) generic backward.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ._helpers import _op, as_tuple_axis, make_binary, make_unary

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "heaviside",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
    "neg", "sign", "floor", "ceil", "round", "trunc", "frac", "reciprocal",
    "square", "sin", "cos", "tan", "tanh", "asin", "acos", "atan", "sinh", "cosh",
    "asinh", "acosh", "atanh", "erf", "erfinv", "digamma", "lgamma",
    "clip", "lerp", "scale", "stanh", "rad2deg", "deg2rad", "angle", "conj", "real", "imag",
    "sum", "mean", "max", "min", "amax", "amin", "prod", "std", "var", "median",
    "nansum", "nanmean", "logsumexp", "all", "any", "count_nonzero",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    "matmul", "dot", "inner", "outer", "addmm", "kron", "trace", "diff",
    "isnan", "isinf", "isfinite", "nan_to_num", "logit", "multiplex",
    "increment", "gcd", "lcm", "logaddexp", "hypot", "ldexp", "copysign",
    "sgn", "take", "renorm", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
]

# ------------------------------------------------------------- elementwise binary

add = make_binary("add", jnp.add)
subtract = make_binary("subtract", jnp.subtract)
multiply = make_binary("multiply", jnp.multiply)
divide = make_binary("divide", jnp.true_divide)
floor_divide = make_binary("floor_divide", jnp.floor_divide)
remainder = make_binary("remainder", jnp.remainder)
mod = remainder
maximum = make_binary("maximum", jnp.maximum)
minimum = make_binary("minimum", jnp.minimum)
fmax = make_binary("fmax", jnp.fmax)
fmin = make_binary("fmin", jnp.fmin)
atan2 = make_binary("atan2", jnp.arctan2)
logaddexp = make_binary("logaddexp", jnp.logaddexp)
hypot = make_binary("hypot", jnp.hypot)
copysign = make_binary("copysign", jnp.copysign)
gcd = make_binary("gcd", jnp.gcd)
lcm = make_binary("lcm", jnp.lcm)
bitwise_and = make_binary("bitwise_and", jnp.bitwise_and)
bitwise_or = make_binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = make_binary("bitwise_xor", jnp.bitwise_xor)
heaviside = make_binary("heaviside", jnp.heaviside)


def pow(x, y, name=None):
    return _op("pow", x, y)


register_op("pow", jnp.power)


def ldexp(x, y, name=None):
    return _op("ldexp", x, y)


register_op("ldexp", lambda x, y: x * (2.0 ** y.astype(jnp.float32)))

# ------------------------------------------------------------- elementwise unary

exp = make_unary("exp", jnp.exp)
expm1 = make_unary("expm1", jnp.expm1)
log = make_unary("log", jnp.log)
log2 = make_unary("log2", jnp.log2)
log10 = make_unary("log10", jnp.log10)
log1p = make_unary("log1p", jnp.log1p)
sqrt = make_unary("sqrt", jnp.sqrt)
rsqrt = make_unary("rsqrt", jax.lax.rsqrt)
abs = make_unary("abs", jnp.abs)
neg = make_unary("neg", jnp.negative)
sign = make_unary("sign", jnp.sign)
sgn = sign
floor = make_unary("floor", jnp.floor)
ceil = make_unary("ceil", jnp.ceil)
round = make_unary("round", jnp.round)
trunc = make_unary("trunc", jnp.trunc)
frac = make_unary("frac", lambda x: x - jnp.trunc(x))
reciprocal = make_unary("reciprocal", jnp.reciprocal)
square = make_unary("square", jnp.square)
sin = make_unary("sin", jnp.sin)
cos = make_unary("cos", jnp.cos)
tan = make_unary("tan", jnp.tan)
tanh = make_unary("tanh", jnp.tanh)
asin = make_unary("asin", jnp.arcsin)
acos = make_unary("acos", jnp.arccos)
atan = make_unary("atan", jnp.arctan)
sinh = make_unary("sinh", jnp.sinh)
cosh = make_unary("cosh", jnp.cosh)
asinh = make_unary("asinh", jnp.arcsinh)
acosh = make_unary("acosh", jnp.arccosh)
atanh = make_unary("atanh", jnp.arctanh)
erf = make_unary("erf", jax.scipy.special.erf)
erfinv = make_unary("erfinv", jax.scipy.special.erfinv)
digamma = make_unary("digamma", jax.scipy.special.digamma)
lgamma = make_unary("lgamma", jax.scipy.special.gammaln)
rad2deg = make_unary("rad2deg", jnp.rad2deg)
deg2rad = make_unary("deg2rad", jnp.deg2rad)
angle = make_unary("angle", jnp.angle)
conj = make_unary("conj", jnp.conj)
real = make_unary("real", jnp.real)
imag = make_unary("imag", jnp.imag)
isnan = make_unary("isnan", jnp.isnan)
isinf = make_unary("isinf", jnp.isinf)
isfinite = make_unary("isfinite", jnp.isfinite)
bitwise_not = make_unary("bitwise_not", jnp.bitwise_not)


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return _op("clip", x, min=None if lo is None else float(lo),
               max=None if hi is None else float(hi))


register_op("clip", lambda x, min=None, max=None: jnp.clip(x, min, max))


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        weight = float(weight)
        return _op("lerp_scalar", x, y, weight=weight)
    return _op("lerp", x, y, weight)


register_op("lerp", lambda x, y, w: x + w * (y - x))
register_op("lerp_scalar", lambda x, y, weight=0.5: x + weight * (y - x))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    return _op("scale", x, scale=float(scale), bias=float(bias),
               bias_after_scale=bool(bias_after_scale))


def _scale_fwd(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


register_op("scale", _scale_fwd)

stanh = make_unary("stanh", lambda x: 1.7159 * jnp.tanh(0.66667 * x))


def logit(x, eps=None, name=None):
    return _op("logit", x, eps=eps)


def _logit_fwd(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


register_op("logit", _logit_fwd)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _op("nan_to_num", x, nan=float(nan),
               posinf=None if posinf is None else float(posinf),
               neginf=None if neginf is None else float(neginf))


register_op("nan_to_num", lambda x, nan=0.0, posinf=None, neginf=None:
            jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf))


def increment(x, value=1.0, name=None):
    out = _op("scale", x, scale=1.0, bias=float(value), bias_after_scale=True)
    x._set_value_inplace(out.value())
    return x

# ------------------------------------------------------------- reductions


def _reduction(name, jfn):
    def fwd(x, axis=None, keepdim=False):
        return jfn(x, axis=axis, keepdims=keepdim)

    register_op(name, fwd)

    def wrapper(x, axis=None, keepdim=False, name=None):
        return _op(name_, x, axis=as_tuple_axis(axis), keepdim=bool(keepdim))

    name_ = name
    wrapper.__name__ = name
    return wrapper


sum = _reduction("sum", jnp.sum)
mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod)
max = _reduction("max", jnp.max)
min = _reduction("min", jnp.min)
amax = _reduction("amax", jnp.max)
amin = _reduction("amin", jnp.min)
nansum = _reduction("nansum", jnp.nansum)
nanmean = _reduction("nanmean", jnp.nanmean)
all = _reduction("all", jnp.all)
any = _reduction("any", jnp.any)
median = _reduction("median", jnp.median)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _op("std", x, axis=as_tuple_axis(axis), unbiased=bool(unbiased),
               keepdim=bool(keepdim))


register_op("std", lambda x, axis=None, unbiased=True, keepdim=False:
            jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _op("var", x, axis=as_tuple_axis(axis), unbiased=bool(unbiased),
               keepdim=bool(keepdim))


register_op("var", lambda x, axis=None, unbiased=True, keepdim=False:
            jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _op("logsumexp", x, axis=as_tuple_axis(axis), keepdim=bool(keepdim))


register_op("logsumexp", lambda x, axis=None, keepdim=False:
            jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _op("count_nonzero", x, axis=as_tuple_axis(axis), keepdim=bool(keepdim))


register_op("count_nonzero", lambda x, axis=None, keepdim=False:
            jnp.count_nonzero(x, axis=axis, keepdims=keepdim).astype(jnp.int32))

# ------------------------------------------------------------- scans

def cumsum(x, axis=None, dtype=None, name=None):
    return _op("cumsum", x, axis=None if axis is None else int(axis))


register_op("cumsum", lambda x, axis=None:
            jnp.cumsum(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))


def cumprod(x, dim=None, dtype=None, name=None):
    return _op("cumprod", x, axis=None if dim is None else int(dim))


register_op("cumprod", lambda x, axis=None:
            jnp.cumprod(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))


def logcumsumexp(x, axis=None, name=None):
    return _op("logcumsumexp", x, axis=None if axis is None else int(axis))


register_op("logcumsumexp", lambda x, axis=None:
            jax.lax.cumlogsumexp(x.reshape(-1) if axis is None else x,
                                 axis=0 if axis is None else axis))


def cummax(x, axis=None, dtype="int64", name=None):
    arr = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    a = arr.reshape(-1) if axis is None else arr
    ax = 0 if axis is None else int(axis)
    out = jax.lax.cummax(a, axis=ax)
    vals = _op("cummax_vals", x, axis=None if axis is None else int(axis))
    return vals, Tensor(_cum_arg_indices(a, out, ax).astype(jnp.int32))


register_op("cummax_vals", lambda x, axis=None:
            jax.lax.cummax(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))
register_op("cummin_vals", lambda x, axis=None:
            jax.lax.cummin(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))


def _cum_arg_indices(a, out, ax):
    n = a.shape[ax]
    ar = jnp.arange(n)
    shape = [1] * a.ndim
    shape[ax] = n
    pos = ar.reshape(shape)
    match = (a == out)
    return jax.lax.cummax(jnp.where(match, pos, -1), axis=ax)


def cummin(x, axis=None, dtype="int64", name=None):
    arr = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    a = arr.reshape(-1) if axis is None else arr
    ax = 0 if axis is None else int(axis)
    out = jax.lax.cummin(a, axis=ax)
    vals = _op("cummin_vals", x, axis=None if axis is None else int(axis))
    return vals, Tensor(_cum_arg_indices(a, out, ax).astype(jnp.int32))

# ------------------------------------------------------------- linalg-ish


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _op("matmul", x, y, transpose_x=bool(transpose_x), transpose_y=bool(transpose_y))


def _matmul_fwd(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


register_op("matmul", _matmul_fwd)


def dot(x, y, name=None):
    return _op("dot", x, y)


register_op("dot", lambda x, y: jnp.sum(x * y, axis=-1))


def inner(x, y, name=None):
    return _op("inner", x, y)


register_op("inner", jnp.inner)


def outer(x, y, name=None):
    return _op("outer", x, y)


register_op("outer", lambda x, y: jnp.outer(x.reshape(-1), y.reshape(-1)))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _op("addmm", input, x, y, beta=float(beta), alpha=float(alpha))


register_op("addmm", lambda inp, x, y, beta=1.0, alpha=1.0:
            beta * inp + alpha * jnp.matmul(x, y))


def kron(x, y, name=None):
    return _op("kron", x, y)


register_op("kron", jnp.kron)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _op("trace", x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


register_op("trace", lambda x, offset=0, axis1=0, axis2=1:
            jnp.trace(x, offset, axis1, axis2))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    spec = []
    if prepend is not None:
        args.append(prepend)
        spec.append("prepend")
    if append is not None:
        args.append(append)
        spec.append("append")
    return _op("diff", *args, n=int(n), axis=int(axis), spec=tuple(spec))


def _diff_fwd(x, *extra, n=1, axis=-1, spec=()):
    kw = {}
    for name, arr in zip(spec, extra):
        kw[name] = arr
    return jnp.diff(x, n=n, axis=axis, **kw)


register_op("diff", _diff_fwd)


def multiplex(inputs, index, name=None):
    stacked_args = list(inputs) + [index]
    return _op("multiplex", *stacked_args)


def _multiplex_fwd(*args):
    *ins, idx = args
    stacked = jnp.stack(ins, axis=0)  # [K, N, ...]
    sel = idx.reshape(-1).astype(jnp.int32)  # [N]
    rows = jnp.arange(sel.shape[0])
    return stacked[sel, rows]


register_op("multiplex", _multiplex_fwd)


def take(x, index, mode="raise", name=None):
    return _op("take", x, index, mode=str(mode))


def _take_fwd(x, index, mode="raise"):
    flat = x.reshape(-1)
    n = flat.shape[0]
    idx = index
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    elif mode == "clip":
        idx = jnp.clip(idx, -n, n - 1)
    idx = jnp.where(idx < 0, idx + n, idx)
    return jnp.take(flat, idx.astype(jnp.int32))


register_op("take", _take_fwd, nondiff_inputs=(1,))


def renorm(x, p, axis, max_norm, name=None):
    return _op("renorm", x, p=float(p), axis=int(axis), max_norm=float(max_norm))


def _renorm_fwd(x, p=2.0, axis=0, max_norm=1.0):
    dims = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


register_op("renorm", _renorm_fwd)
