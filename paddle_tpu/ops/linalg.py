"""Linear algebra (reference: python/paddle/tensor/linalg.py, paddle.linalg namespace)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ._helpers import _op, as_tuple_axis

__all__ = [
    "norm", "vector_norm", "matrix_norm", "dist", "cond", "matrix_rank",
    "cholesky", "qr", "svd", "svdvals", "eig", "eigh", "eigvals", "eigvalsh",
    "inv", "pinv", "solve", "triangular_solve", "cholesky_solve", "lstsq", "lu",
    "det", "slogdet", "matrix_power", "mv", "bmm", "bincount", "histogram",
    "cross", "cov", "corrcoef", "einsum", "multi_dot", "householder_product",
    "matrix_exp", "pca_lowrank",
]


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) and len(axis) == 2 else 2.0
    if isinstance(p, str):
        return _op("norm_fro", x, axis=as_tuple_axis(axis), keepdim=bool(keepdim))
    return _op("norm_p", x, p=float(p), axis=as_tuple_axis(axis), keepdim=bool(keepdim))


def _norm_fro(x, axis=None, keepdim=False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


def _norm_p(x, p=2.0, axis=None, keepdim=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


register_op("norm_fro", _norm_fro)
register_op("norm_p", _norm_p)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return _op("norm_p", x, p=float(p), axis=as_tuple_axis(axis), keepdim=bool(keepdim))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    if p == "fro":
        return _op("norm_fro", x, axis=as_tuple_axis(axis), keepdim=bool(keepdim))
    return _op("matrix_norm_ord", x, p=p if isinstance(p, str) else float(p),
               axis=as_tuple_axis(axis), keepdim=bool(keepdim))


register_op("matrix_norm_ord", lambda x, p=2, axis=(-2, -1), keepdim=False:
            jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim))


def dist(x, y, p=2, name=None):
    return _op("dist", x, y, p=float(p))


register_op("dist", lambda x, y, p=2.0: _norm_p(x - y, p=p))


def cond(x, p=None, name=None):
    return Tensor(jnp.linalg.cond(x.value(), p=p))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(x.value(), tol=tol))


def cholesky(x, upper=False, name=None):
    return _op("cholesky", x, upper=bool(upper))


register_op("cholesky", lambda x, upper=False:
            jnp.linalg.cholesky(x) if not upper
            else jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2).conj())


def qr(x, mode="reduced", name=None):
    outs = _op("qr", x, mode=str(mode))
    return outs if isinstance(outs, tuple) else outs


def _qr_fwd(x, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode if mode != "r" else "reduced")
    if mode == "r":
        return r
    return q, r


register_op("qr", _qr_fwd)


def svd(x, full_matrices=False, name=None):
    return _op("svd", x, full_matrices=bool(full_matrices))


register_op("svd", lambda x, full_matrices=False:
            tuple(jnp.linalg.svd(x, full_matrices=full_matrices)))


def svdvals(x, name=None):
    return _op("svdvals", x)


register_op("svdvals", lambda x: jnp.linalg.svd(x, compute_uv=False))


def eig(x, name=None):
    # CPU-only in jax; run on host like reference's CPU fallback for LAPACK ops
    import numpy as np
    w, v = np.linalg.eig(x.numpy())
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    import numpy as np
    return Tensor(np.linalg.eigvals(x.numpy()))


def eigh(x, UPLO="L", name=None):
    outs = _op("eigh", x, UPLO=str(UPLO))
    return outs


register_op("eigh", lambda x, UPLO="L": tuple(jnp.linalg.eigh(x, UPLO=UPLO)))


def eigvalsh(x, UPLO="L", name=None):
    return _op("eigvalsh", x, UPLO=str(UPLO))


register_op("eigvalsh", lambda x, UPLO="L": jnp.linalg.eigvalsh(x, UPLO=UPLO))


def inv(x, name=None):
    return _op("inv", x)


register_op("inv", jnp.linalg.inv)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _op("pinv", x, rcond=float(rcond), hermitian=bool(hermitian))


register_op("pinv", lambda x, rcond=1e-15, hermitian=False:
            jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian))


def solve(x, y, name=None):
    return _op("solve", x, y)


register_op("solve", jnp.linalg.solve)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return _op("triangular_solve", x, y, upper=bool(upper), transpose=bool(transpose),
               unitriangular=bool(unitriangular))


register_op("triangular_solve", lambda x, y, upper=True, transpose=False, unitriangular=False:
            jax.scipy.linalg.solve_triangular(x, y, lower=not upper, trans=1 if transpose else 0,
                                              unit_diagonal=unitriangular))


def cholesky_solve(x, y, upper=False, name=None):
    return _op("cholesky_solve", x, y, upper=bool(upper))


register_op("cholesky_solve", lambda x, y, upper=False:
            jax.scipy.linalg.cho_solve((y, not upper), x))


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x.value(), y.value(), rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x.value())
    outs = [Tensor(lu_mat), Tensor((piv + 1).astype(jnp.int32))]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), jnp.int32)))
    return tuple(outs)


def det(x, name=None):
    return _op("det", x)


register_op("det", jnp.linalg.det)


def slogdet(x, name=None):
    return _op("slogdet", x)


register_op("slogdet", lambda x: tuple(jnp.linalg.slogdet(x)))


def matrix_power(x, n, name=None):
    return _op("matrix_power", x, n=int(n))


register_op("matrix_power", lambda x, n=1: jnp.linalg.matrix_power(x, n))


def matrix_exp(x, name=None):
    return _op("matrix_exp", x)


register_op("matrix_exp", jax.scipy.linalg.expm)


def mv(x, vec, name=None):
    return _op("mv", x, vec)


register_op("mv", jnp.matmul)


def bmm(x, y, name=None):
    return _op("bmm", x, y)


register_op("bmm", jnp.matmul)


def bincount(x, weights=None, minlength=0, name=None):
    import numpy as np
    w = weights.numpy() if weights is not None else None
    return Tensor(np.bincount(x.numpy(), weights=w, minlength=int(minlength)))


def histogram(input, bins=100, min=0, max=0, name=None):
    import numpy as np
    rng_arg = None if (min == 0 and max == 0) else (float(min), float(max))
    hist, _ = np.histogram(input.numpy(), bins=int(bins), range=rng_arg)
    return Tensor(hist.astype(np.int32))


def cross(x, y, axis=9, name=None):
    ax = axis
    if ax == 9:
        shape = x.shape
        ax = next((i for i, s in enumerate(shape) if s == 3), -1)
    return _op("cross", x, y, axis=int(ax))


register_op("cross", lambda x, y, axis=-1: jnp.cross(x, y, axis=axis))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    args = [x]
    if fweights is not None:
        args.append(fweights)
    if aweights is not None:
        args.append(aweights)
    return Tensor(jnp.cov(x.value(), rowvar=rowvar, ddof=1 if ddof else 0,
                          fweights=None if fweights is None else fweights.value(),
                          aweights=None if aweights is None else aweights.value()))


def corrcoef(x, rowvar=True, name=None):
    return Tensor(jnp.corrcoef(x.value(), rowvar=rowvar))


def einsum(equation, *operands, name=None):
    ops_ = list(operands)
    if len(ops_) == 1 and isinstance(ops_[0], (list, tuple)):
        ops_ = list(ops_[0])
    return _op("einsum", *ops_, equation=str(equation))


register_op("einsum", lambda *xs, equation="": jnp.einsum(equation, *xs))


def multi_dot(x, name=None):
    return _op("multi_dot", *list(x))


register_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(list(xs)))


def householder_product(x, tau, name=None):
    # A = H_1 H_2 ... H_k, H_i = I - tau_i v_i v_i^T (jax: geqrf companion)
    return Tensor(jax.lax.linalg.householder_product(x.value(), tau.value()))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    a = x.value()
    if q is None:
        q = min(6, a.shape[-2], a.shape[-1])
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return Tensor(u[..., :q]), Tensor(s[..., :q]), Tensor(jnp.swapaxes(vt, -1, -2)[..., :q])


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu() results into P, L, U (reference lu_unpack; 2-D inputs —
    this repo's lu() emits 1-based LAPACK pivots, handled here)."""
    import jax.numpy as jnp
    import numpy as _np
    from ..core.tensor import Tensor
    lu_v = x.value() if hasattr(x, "value") else jnp.asarray(x)
    if lu_v.ndim != 2:
        raise ValueError("lu_unpack supports 2-D factors (got "
                         f"{lu_v.ndim}-D); unbatch first")
    piv = _np.asarray(y.numpy() if hasattr(y, "numpy") else y).reshape(-1)
    piv = piv.astype(_np.int64) - 1          # 1-based LAPACK -> 0-based
    m, n = lu_v.shape
    k = min(m, n)
    L = jnp.tril(lu_v[:, :k], -1) + jnp.eye(m, k, dtype=lu_v.dtype)
    U = jnp.triu(lu_v[:k, :])
    p_np = _np.arange(m)
    for i, pv in enumerate(piv[:k]):
        p_np[[i, pv]] = p_np[[pv, i]]
    P = jnp.eye(m, dtype=lu_v.dtype)[:, p_np]
    return Tensor(P), Tensor(L), Tensor(U)
