"""Comparison/logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import _op, make_binary, make_unary

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "allclose", "isclose", "equal_all", "is_empty", "is_tensor",
]

equal = make_binary("equal", jnp.equal)
not_equal = make_binary("not_equal", jnp.not_equal)
less_than = make_binary("less_than", jnp.less)
less_equal = make_binary("less_equal", jnp.less_equal)
greater_than = make_binary("greater_than", jnp.greater)
greater_equal = make_binary("greater_equal", jnp.greater_equal)
logical_and = make_binary("logical_and", jnp.logical_and)
logical_or = make_binary("logical_or", jnp.logical_or)
logical_xor = make_binary("logical_xor", jnp.logical_xor)
logical_not = make_unary("logical_not", jnp.logical_not)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _op("isclose", x, y, rtol=float(rtol), atol=float(atol),
               equal_nan=bool(equal_nan))


from ..core.dispatch import register_op as _reg

_reg("isclose", lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
     jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _op("allclose", x, y, rtol=float(rtol), atol=float(atol),
               equal_nan=bool(equal_nan))


_reg("allclose", lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
     jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan))


def equal_all(x, y, name=None):
    return _op("equal_all", x, y)


_reg("equal_all", lambda x, y: jnp.array_equal(x, y))


def is_empty(x, name=None):
    return Tensor(np.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
