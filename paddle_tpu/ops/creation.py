"""Tensor creation ops (reference: python/paddle/tensor/creation.py + random.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as rng
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, to_tensor  # re-export to_tensor
from ._helpers import static_int_list

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye", "rand",
    "randn", "randint", "randint_like", "uniform", "normal", "standard_normal",
    "randperm", "bernoulli", "multinomial", "tril", "triu", "diag", "diagflat",
    "meshgrid", "assign", "clone", "numel", "poisson",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().reshape(-1))
    if isinstance(shape, (list, tuple)):
        return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    return (int(shape),)


def _dt(dtype, default=jnp.float32):
    d = convert_dtype(dtype)
    return d if d is not None else default


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        arr = jnp.full(_shape(shape), fill_value)
        if arr.dtype == jnp.float64:
            arr = arr.astype(jnp.float32)
        return Tensor(arr)
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(x.shape), _dt(dtype, x.dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(x.shape), _dt(dtype, x.dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full(tuple(x.shape), fill_value, _dt(dtype, x.dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = jnp.int32 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) else jnp.float32
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)
    return Tensor(jnp.linspace(start, stop, num, dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


# ------------------------------------------------------------------ random

def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rng.split_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rng.split_key(), _shape(shape), _dt(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean.value() if isinstance(mean, Tensor) else mean
        s = std.value() if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(rng.split_key(), out_shape) * s + m)
    return Tensor(jax.random.normal(rng.split_key(), _shape(shape)) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else rng.split_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=float(min), maxval=float(max)))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(rng.split_key(), _shape(shape), int(low), int(high),
                                     _dt(dtype, jnp.int32)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(rng.split_key(), tuple(x.shape), int(low), int(high),
                                     _dt(dtype, x.dtype)))


def randperm(n, dtype=None, name=None):
    return Tensor(jax.random.permutation(rng.split_key(), int(n)).astype(
        _dt(dtype, jnp.int32)))


def bernoulli(x, name=None):
    p = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(rng.split_key(), p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    p = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(rng.split_key(), logits, axis=-1,
                                     shape=(*p.shape[:-1], int(num_samples)))
    else:
        key = rng.split_key()
        z = jax.random.gumbel(key, p.shape)
        _, out = jax.lax.top_k(logits + z, int(num_samples))
    return Tensor(out.astype(jnp.int32))


def poisson(x, name=None):
    lam = x.value() if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(rng.split_key(), lam).astype(lam.dtype))


# ------------------------------------------------------------------ structured

def tril(x, diagonal=0, name=None):
    from ._helpers import _op
    return _op("tril", x, diagonal=int(diagonal))


def triu(x, diagonal=0, name=None):
    from ._helpers import _op
    return _op("triu", x, diagonal=int(diagonal))


def diag(x, offset=0, padding_value=0, name=None):
    from ._helpers import _op
    return _op("diag", x, offset=int(offset), padding_value=padding_value)


def diagflat(x, offset=0, name=None):
    from ._helpers import _op
    return _op("diagflat", x, offset=int(offset))


def meshgrid(*args, **kwargs):
    arrays = [a.value() if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    data = x.value() if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if data.dtype == jnp.float64:
        data = data.astype(jnp.float32)
    if output is not None:
        output.set_value(data)
        return output
    return Tensor(data)


def clone(x, name=None):
    from ._helpers import _op
    return _op("clone", x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int32))


# register the dispatchable structured ops
from ..core.dispatch import register_op as _reg

_reg("tril", lambda x, diagonal=0: jnp.tril(x, diagonal))
_reg("triu", lambda x, diagonal=0: jnp.triu(x, diagonal))
_reg("diag", lambda x, offset=0, padding_value=0:
     jnp.diag(x, offset) if x.ndim == 1 else jnp.diagonal(x, offset, -2, -1))
_reg("diagflat", lambda x, offset=0: jnp.diagflat(x, offset))
_reg("clone", lambda x: x + jnp.zeros((), x.dtype))
