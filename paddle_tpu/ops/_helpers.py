"""Shared plumbing for op definitions.

Reference analog: the YAML op schema + generated API layer (phi/api/yaml/ops.yaml,
phi/api/yaml/generator/api_base.py:1187). Instead of YAML→C++ codegen, each op here is a
jax-traceable forward registered with core.dispatch; factories below stamp out the
elementwise families the way the reference stamps kernels from macros.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op, register_op
from ..core.tensor import Tensor


def _op(name, *tensors, **attrs):
    return apply_op(name, tensors, attrs)


def make_unary(name, fn):
    register_op(name, fn)

    def wrapper(x, name=None):
        return _op(name_, x)

    name_ = name
    wrapper.__name__ = name
    wrapper.__qualname__ = name
    wrapper.__doc__ = f"Elementwise {name} (TPU-native; lowers to XLA)."
    return wrapper


def make_binary(name, fn):
    register_op(name, fn)

    def wrapper(x, y, name=None):
        return _op(name_, x, y)

    name_ = name
    wrapper.__name__ = name
    wrapper.__qualname__ = name
    wrapper.__doc__ = f"Elementwise {name} with numpy broadcasting."
    return wrapper


def as_tuple_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(v) for v in axis.numpy().reshape(-1))
    return int(axis)


def static_int_list(v):
    if isinstance(v, Tensor):
        return tuple(int(x) for x in v.numpy().reshape(-1))
    if isinstance(v, (list, tuple)):
        return tuple(int(x.item()) if isinstance(x, Tensor) else int(x) for x in v)
    return (int(v),)
