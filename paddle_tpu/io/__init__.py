from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split, get_worker_info, WorkerInfo,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler, BatchSampler,
    DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .device_loader import DeviceLoader, batch_sharding, stack_microbatches  # noqa: F401
from .bucketing import (  # noqa: F401
    DEFAULT_BOUNDARIES, bucket_length, pad_to_bucket, padding_attn_mask,
    BucketingCollate, LengthGroupedBatchSampler,
)
