"""Datasets (reference: python/paddle/io/ dataset classes in fluid/dataloader)."""
from __future__ import annotations

import bisect
from typing import List

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        assert len(lengths) == 1, "all tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(len(dataset))
    out = []
    offset = 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class WorkerInfo:
    """Worker context for IterableDataset sharding (reference
    fluid/dataloader/worker.py get_worker_info)."""

    def __init__(self, id: int, num_workers: int, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker: (id, num_workers) so an IterableDataset can
    split its stream; None in the main process (reference get_worker_info)."""
    return _worker_info


def _set_worker_info(info):
    global _worker_info
    _worker_info = info
